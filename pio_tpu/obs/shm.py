"""Fixed-layout mmapped metrics segment for SO_REUSEPORT pool serving.

Problem: each pool worker is its own process, so a Prometheus scrape
(kernel-balanced to ONE listener) used to see one worker's shard of the
counters — silently underreporting QPS by the worker count.

Solution: the pool supervisor creates one small file of fixed layout;
every worker mmaps it and mirrors its counter/histogram-bucket cells
into its OWN per-worker stripe (single writer per stripe — no cross-
process locking needed; float64 slot writes are naturally aligned).
Reading a pool-wide total sums the slot across stripes. Works with the
``spawn`` multiprocessing context because workers reopen by path.

Layout (little-endian)::

    0   8s  magic  b"PIOMETR2"
    8   I   n_workers
    12  I   slots_per_worker
    16  16x reserved
    32  n_workers generation float64 (stripe ownership, see below)
    32+8*n_workers   n_workers stripes of slots_per_worker float64 each

Stripe generations (ISSUE 11): a respawned worker *adopts* its
predecessor's stripe (counters keep their totals), which is correct for
pool-wide sums but invisible to an external aggregator — a counter that
jumps mid-scrape could be traffic or could be adoption. The supervisor
owns the generation word: ``set_generation`` to ``1`` at first spawn,
``bump_generation`` on every respawn, and ``retire`` (negates the
value) when a worker's respawn budget is spent and its stripe is frozen
at its last totals. Workers export their stripe's generation as the
``pio_tpu_pool_stripe_generation`` gauge, so a scraper that sees the
generation move knows any counter discontinuity is adoption, not load —
and a negative generation marks a retired stripe whose (retained, still
summed) totals will never move again.

Torn reads are possible in theory (a reader may catch a stripe between
two writes of one histogram observe) — acceptable for monitoring: every
individual slot is written atomically, so counters are never garbage,
and bucket counts lag each other by at most one in-flight observation.
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import List

MAGIC = b"PIOMETR2"
HEADER_BYTES = 32
#: default stripe width — the query server's pool-bound families
#: (request/error counters + stage histogram cells + latency histogram
#: + the shape-bucket dispatch/retrace + batch-lane counters) need
#: ~150 slots; 384 leaves headroom for growth
DEFAULT_SLOTS = 384


class PoolMetricsSegment:
    """One mmapped metrics file; create in the supervisor, open in
    every worker (and in the supervisor for debugging)."""

    def __init__(self, path: str, n_workers: int, slots_per_worker: int,
                 _file=None, _map=None):
        self.path = path
        self.n_workers = n_workers
        self.slots_per_worker = slots_per_worker
        self._f = _file
        self._m = _map

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def create(cls, path: str, n_workers: int,
               slots_per_worker: int = DEFAULT_SLOTS) -> "PoolMetricsSegment":
        if n_workers < 1 or slots_per_worker < 1:
            raise ValueError("n_workers and slots_per_worker must be >= 1")
        size = cls._size(n_workers, slots_per_worker)
        with open(path, "wb") as f:
            f.write(MAGIC)
            # pio: frame=metrics-header
            f.write(struct.pack("<II", n_workers, slots_per_worker))
            f.write(b"\0" * (size - 16))
        return cls.open(path)

    @classmethod
    def open(cls, path: str) -> "PoolMetricsSegment":
        f = open(path, "r+b")
        try:
            head = f.read(HEADER_BYTES)
            if len(head) < HEADER_BYTES or head[:8] != MAGIC:
                raise ValueError(f"{path}: not a pool metrics segment")
            # pio: frame=metrics-header
            n_workers, slots = struct.unpack_from("<II", head, 8)
            m = mmap.mmap(f.fileno(), cls._size(n_workers, slots))
        except BaseException:
            f.close()
            raise
        return cls(path, n_workers, slots, _file=f, _map=m)

    @staticmethod
    def _size(n_workers: int, slots_per_worker: int) -> int:
        return HEADER_BYTES + n_workers * 8 + n_workers * slots_per_worker * 8

    def close(self) -> None:
        if self._m is not None:
            self._m.close()
            self._m = None
        if self._f is not None:
            self._f.close()
            self._f = None

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # -- stripe generations ------------------------------------------------
    def _gen_off(self, worker_idx: int) -> int:
        if not (0 <= worker_idx < self.n_workers):
            raise IndexError(f"worker {worker_idx} of {self.n_workers}")
        return HEADER_BYTES + worker_idx * 8

    def generation(self, worker_idx: int) -> int:
        """0 = never owned; N>0 = owned, adopted N-1 times; -N = stripe
        retired at generation N (frozen totals, still summed)."""
        # pio: frame=metrics-stripe
        return int(struct.unpack_from(
            "<d", self._m, self._gen_off(worker_idx)
        )[0])

    def set_generation(self, worker_idx: int, gen: int) -> None:
        # pio: frame=metrics-stripe
        struct.pack_into(
            "<d", self._m, self._gen_off(worker_idx), float(gen)
        )

    def bump_generation(self, worker_idx: int) -> int:
        """Supervisor-side: the stripe is about to be adopted by a
        replacement process. Returns the new generation."""
        gen = abs(self.generation(worker_idx)) + 1
        self.set_generation(worker_idx, gen)
        return gen

    def retire_stripe(self, worker_idx: int) -> int:
        """Supervisor-side: the worker is permanently retired; negate
        the generation so scrapers know the stripe's totals are frozen
        (retained in sums — retirement must not shrink pool counters)."""
        gen = -abs(self.generation(worker_idx))
        self.set_generation(worker_idx, gen)
        return gen

    def generations(self) -> List[int]:
        return [self.generation(w) for w in range(self.n_workers)]

    # -- slots -------------------------------------------------------------
    def _off(self, worker_idx: int, slot: int) -> int:
        if not (0 <= worker_idx < self.n_workers):
            raise IndexError(f"worker {worker_idx} of {self.n_workers}")
        if not (0 <= slot < self.slots_per_worker):
            raise IndexError(f"slot {slot} of {self.slots_per_worker}")
        return (HEADER_BYTES + self.n_workers * 8
                + (worker_idx * self.slots_per_worker + slot) * 8)

    def set(self, worker_idx: int, slot: int, v: float) -> None:
        struct.pack_into("<d", self._m, self._off(worker_idx, slot), v)  # pio: frame=metrics-stripe

    def read(self, worker_idx: int, slot: int) -> float:
        # pio: frame=metrics-stripe
        return struct.unpack_from("<d", self._m, self._off(worker_idx, slot))[0]

    def sum_slot(self, slot: int) -> float:
        """Pool-wide total: the slot summed over every worker stripe."""
        return sum(self.read(w, slot) for w in range(self.n_workers))

    def read_all(self, slot: int) -> List[float]:
        return [self.read(w, slot) for w in range(self.n_workers)]
