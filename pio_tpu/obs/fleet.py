"""Fleet telemetry aggregator — cross-host metric federation (ISSUE 11).

One process per engine (PAPER.md's layer map) means fleet state lives
scattered across N ``/metrics`` endpoints. This module is the pull side
of the telemetry plane: a :class:`FleetAggregator` scrapes each member's
``/metrics``, ``/readyz``, ``/slo.json``, ``/storage.json`` (and
``/stats.json`` best-effort, for shard/residency placement) on a
jittered interval, then

- re-exposes the union of every member's metrics on its host registry
  with a ``pio_tpu_member="host:port"`` label injected per sample
  (:func:`pio_tpu.obs.promparse.with_labels` +
  :func:`pio_tpu.obs.promparse.merge` — counters sum, histograms merge
  bucket-wise, so one scrape of the aggregator equals the sum of the
  per-member scrapes), and
- builds the ``/fleet.json`` payload (:meth:`FleetAggregator.fleet_payload`)
  — the documented contract the ROADMAP-item-2 router consumes: member
  liveness/readiness/staleness, worst SLO burn rate per objective across
  members, partlog topology with per-partition per-follower replication
  lag and fleet-wide min-acked positions, and engine placement.

Staleness semantics: a member that stops answering keeps its last-seen
snapshot (no silent disappearance from the federated sums) and walks
``up -> stale -> down`` as the age of its last good scrape crosses
``stale_after_s`` then ``down_after_s``. A member that has *never*
answered is ``down`` from its first failed scrape.

Own metric families (on the registry passed in):

- ``pio_tpu_fleet_member_up{member}`` — 1 while the member's scrape is
  fresh, else 0;
- ``pio_tpu_fleet_scrape_age_seconds{member}`` — age of the last good
  scrape (-1 until one succeeds);
- ``pio_tpu_fleet_scrapes_total{member}`` — scrape attempts;
- ``pio_tpu_fleet_scrape_errors_total{member,reason}`` — failed scrapes
  by ``unreachable`` / ``http`` / ``parse`` reason.
"""

from __future__ import annotations

import json
import random
import threading
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.obs import promparse
from pio_tpu.obs.metrics import MetricsRegistry, monotonic_s
from pio_tpu.obs.promparse import ParsedMetrics

#: env fallback for ``pio fleet --targets`` / embedded aggregators
TARGETS_ENV = "PIO_TPU_FLEET_TARGETS"
INTERVAL_ENV = "PIO_TPU_FLEET_INTERVAL_S"

DEFAULT_INTERVAL_S = 5.0
#: multiples of the scrape interval after which a silent member is
#: marked stale, then down
STALE_AFTER_INTERVALS = 2.5
DOWN_AFTER_INTERVALS = 5.0


def parse_targets(spec: Optional[str]) -> List[Tuple[str, str]]:
    """``"host:port,http://h2:9001"`` -> ``[(member, base_url), ...]``.
    The member name is always ``host:port`` (the label value); a bare
    target gets an ``http://`` scheme."""
    out: List[Tuple[str, str]] = []
    seen = set()
    for raw in (spec or "").split(","):
        t = raw.strip().rstrip("/")
        if not t:
            continue
        url = t if "://" in t else f"http://{t}"
        member = url.split("://", 1)[1]
        if member in seen:
            continue
        seen.add(member)
        out.append((member, url))
    return out


def _default_fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


class _Member:
    """Scrape state for one fleet member (last-seen data retained)."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url
        self.attempts = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        #: monotonic_s of the last successful /metrics scrape, or None
        self.last_ok: Optional[float] = None
        self.metrics: Optional[ParsedMetrics] = None
        self.ready: Optional[bool] = None
        self.ready_report: Optional[dict] = None
        self.slo: Optional[dict] = None
        self.storage: Optional[dict] = None
        self.stats: Optional[dict] = None
        self.train: Optional[dict] = None
        self.device: Optional[dict] = None
        self.routerd: Optional[dict] = None
        self.rollout: Optional[dict] = None

    def age_s(self) -> Optional[float]:
        if self.last_ok is None:
            return None
        return monotonic_s() - self.last_ok

    def status(self, stale_after_s: float, down_after_s: float) -> str:
        age = self.age_s()
        if age is None:
            return "down" if self.attempts else "unknown"
        if age <= stale_after_s:
            return "up"
        if age <= down_after_s:
            return "stale"
        return "down"

    def role(self) -> str:
        if self.routerd is not None:
            return "router"
        if self.storage is not None and "role" in self.storage:
            return str(self.storage["role"])
        if self.train is not None:
            return "trainer"
        if self.stats is not None and "residency" in self.stats:
            return "query"
        if self.storage is not None:
            return "event"
        return "unknown"


class FleetAggregator:
    """Scrapes fleet members and federates their telemetry.

    ``fetch(url, timeout) -> bytes`` is injectable so failure-mode tests
    can fake members without sockets. ``registry`` is the registry the
    fleet gauges live on and whose ``/metrics`` carries the federated
    re-exposition (a collector is registered on it here).
    """

    def __init__(
        self,
        targets: List[Tuple[str, str]],
        registry: MetricsRegistry,
        interval_s: Optional[float] = None,
        stale_after_s: Optional[float] = None,
        down_after_s: Optional[float] = None,
        timeout_s: float = 3.0,
        fetch: Optional[Callable[[str, float], bytes]] = None,
    ):
        if interval_s is None:
            interval_s = knobs.knob_float(INTERVAL_ENV)
        self.interval_s = interval_s
        self.stale_after_s = (
            stale_after_s if stale_after_s is not None
            else STALE_AFTER_INTERVALS * interval_s
        )
        self.down_after_s = (
            down_after_s if down_after_s is not None
            else DOWN_AFTER_INTERVALS * interval_s
        )
        self.timeout_s = timeout_s
        self._fetch = fetch or _default_fetch
        self._members = [_Member(name, url) for name, url in targets]
        #: completed full scrape passes (readiness gate for fleetd)
        self.passes = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.obs = registry
        self._member_up = registry.gauge(
            "pio_tpu_fleet_member_up",
            "1 while the member's last /metrics scrape is fresh, else 0",
            ("member",),
        )
        self._scrape_age = registry.gauge(
            "pio_tpu_fleet_scrape_age_seconds",
            "Age of the member's last successful scrape (-1 before one)",
            ("member",),
        )
        self._scrapes = registry.counter(
            "pio_tpu_fleet_scrapes_total",
            "Scrape attempts against fleet members",
            ("member",),
        )
        self._scrape_errors = registry.counter(
            "pio_tpu_fleet_scrape_errors_total",
            "Failed member scrapes by reason",
            ("member", "reason"),
        )
        registry.add_collector(self.federated_lines)
        for m in self._members:
            self._member_up.set(0.0, member=m.name)
            self._scrape_age.set(-1.0, member=m.name)

    # -- scraping ----------------------------------------------------------
    def _get_json(self, m: _Member, path: str) -> Optional[dict]:
        try:
            return json.loads(
                self._fetch(m.url + path, self.timeout_s).decode("utf-8")
            )
        except Exception:
            return None

    def _get_ready(self, m: _Member) -> Tuple[Optional[bool], Optional[dict]]:
        """Readiness is carried in the status code (503 when not ready),
        so the HTTPError path is a *successful* probe."""
        try:
            body = self._fetch(m.url + "/readyz", self.timeout_s)
            return True, self._maybe_json(body)
        except urllib.error.HTTPError as e:
            try:
                body = e.read()
            except Exception:
                body = b""
            return False, self._maybe_json(body)
        except Exception:
            return None, None

    @staticmethod
    def _maybe_json(body: bytes) -> Optional[dict]:
        try:
            got = json.loads(body.decode("utf-8"))
            return got if isinstance(got, dict) else None
        except Exception:
            return None

    def scrape_member(self, m: _Member) -> bool:
        """One scrape pass over one member. Returns True when /metrics
        was fetched and parsed; JSON endpoints are best-effort and only
        overwrite the retained snapshot on success."""
        self._scrapes.inc(member=m.name)
        m.attempts += 1
        try:
            raw = self._fetch(m.url + "/metrics", self.timeout_s)
        except urllib.error.HTTPError as e:
            self._record_error(m, "http", f"HTTP {e.code} on /metrics")
            return False
        except Exception as e:
            self._record_error(
                m, "unreachable", f"{type(e).__name__}: {e}"
            )
            return False
        try:
            parsed = promparse.parse_prometheus_text(raw.decode("utf-8"))
            # a fresh registry legitimately exposes only HELP/TYPE heads
            # (labeled families with no cells yet); a body yielding
            # neither samples nor TYPE declarations is not exposition
            if not parsed.samples and not parsed.types and raw.strip():
                raise ValueError("no exposition parsed from non-empty body")
        except Exception as e:
            self._record_error(m, "parse", f"{type(e).__name__}: {e}")
            return False
        ready, report = self._get_ready(m)
        slo = self._get_json(m, "/slo.json")
        storage = self._get_json(m, "/storage.json")
        stats = self._get_json(m, "/stats.json")
        train = self._get_json(m, "/train.json")
        device = self._get_json(m, "/device.json")
        routerd = self._get_json(m, "/router.json")
        rollout = (
            self._get_json(m, "/rollout.json")
            if routerd is not None else None
        )
        with self._lock:
            m.metrics = parsed
            m.last_ok = monotonic_s()
            m.last_error = None
            if ready is not None:
                m.ready, m.ready_report = ready, report
            if slo is not None:
                m.slo = slo
            if storage is not None:
                m.storage = storage
            if stats is not None:
                m.stats = stats
            if train is not None:
                m.train = train
            if device is not None:
                m.device = device
            if routerd is not None:
                m.routerd = routerd
            if rollout is not None:
                m.rollout = rollout
        return True

    def _record_error(self, m: _Member, reason: str, msg: str) -> None:
        m.errors += 1
        m.last_error = msg
        self._scrape_errors.inc(member=m.name, reason=reason)

    def scrape_once(self) -> int:
        """Scrape every member; returns how many answered."""
        ok = 0
        for m in self._members:
            if self.scrape_member(m):
                ok += 1
        self._refresh_gauges()
        self.passes += 1
        return ok

    def _refresh_gauges(self) -> None:
        for m in self._members:
            st = m.status(self.stale_after_s, self.down_after_s)
            self._member_up.set(1.0 if st == "up" else 0.0, member=m.name)
            age = m.age_s()
            self._scrape_age.set(
                round(age, 3) if age is not None else -1.0, member=m.name
            )

    # -- background loop ---------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-scraper", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout_s + 1.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                pass  # a scrape pass must never kill the loop
            # +/-10% jitter so N aggregators don't align on one member
            delay = self.interval_s * random.uniform(0.9, 1.1)
            if self._stop.wait(delay):
                return

    # -- federation --------------------------------------------------------
    def members(self) -> List[_Member]:
        return list(self._members)

    def federated_lines(self) -> List[str]:
        """Exposition lines for the union of every member's last-seen
        metrics, each sample stamped ``pio_tpu_member="name"``. Members
        currently down still contribute their retained snapshot. The
        aggregator's own ``pio_tpu_fleet_*`` families are dropped from
        member snapshots (the host registry already renders them)."""
        with self._lock:
            snaps = [
                (m.name, m.metrics) for m in self._members
                if m.metrics is not None
            ]
        if not snaps:
            return []
        labeled = [
            promparse.with_labels(pm, pio_tpu_member=name)
            for name, pm in snaps
        ]
        merged = promparse.merge(*labeled)
        for key in [
            k for k in merged.samples
            if promparse.family_base(k[0], merged.types).startswith(
                "pio_tpu_fleet_"
            )
        ]:
            merged.samples.pop(key, None)
            merged.exemplars.pop(key, None)
        for fam in [f for f in list(merged.types)
                    if f.startswith("pio_tpu_fleet_")]:
            merged.types.pop(fam, None)
            merged.helps.pop(fam, None)
        return promparse.render(merged)

    # -- /fleet.json -------------------------------------------------------
    # pio: endpoint=/fleet.json
    def fleet_payload(self) -> dict:
        """The router contract (documented in docs/observability.md)."""
        with self._lock:
            members = [self._member_entry(m) for m in self._members]
            slo = self._slo_rollup()
            partlog = self._partlog_rollup()
            placement = self._placement()
            devices = self._devices_rollup()
        counts = {"up": 0, "stale": 0, "down": 0, "unknown": 0}
        for e in members:
            counts[e["status"]] = counts.get(e["status"], 0) + 1
        return {
            "fleet": {
                "members": len(members),
                "up": counts["up"],
                "stale": counts["stale"],
                "down": counts["down"] + counts["unknown"],
                "scrapeIntervalSeconds": self.interval_s,
                "staleAfterSeconds": self.stale_after_s,
                "downAfterSeconds": self.down_after_s,
            },
            "members": members,
            "slo": slo,
            "partlog": partlog,
            "placement": placement,
            "devices": devices,
        }

    def _member_entry(self, m: _Member) -> dict:
        age = m.age_s()
        training = None
        if m.train is not None:
            # compact view of the member's /train.json (full payload on
            # the member itself; the fleet view carries the progress row)
            training = {
                "runId": m.train.get("runId"),
                "phase": m.train.get("phase"),
                "algo": m.train.get("algo"),
                "step": m.train.get("step"),
                "totalSteps": m.train.get("totalSteps"),
                "epoch": m.train.get("epoch"),
                "progress": m.train.get("progress"),
                "etaSeconds": m.train.get("etaSeconds"),
                "loss": m.train.get("loss"),
            }
        devices = None
        if m.device is not None:
            # compact view of the member's /device.json (full payload on
            # the member; the fleet row carries the memory-pressure facts
            # a budget-driven eviction policy steers by)
            rows = m.device.get("devices") or []
            devices = {
                "mode": m.device.get("mode"),
                "count": len(rows),
                "bytesInUse": sum(
                    int(r.get("bytesInUse") or 0) for r in rows
                ),
                "peakBytes": max(
                    (int(r.get("peakBytes") or 0) for r in rows),
                    default=0,
                ),
                "budgetBytes": m.device.get("budgetBytes"),
                "headroomBytes": m.device.get("headroomBytes"),
                "generation": m.device.get("generation"),
                "compiles": (m.device.get("compiles") or {}).get("total"),
            }
        slo = None
        if m.slo is not None:
            # per-member worst burn across its objectives: the serving
            # router's spreading weight (the fleet-level rollup only
            # names the single worst member per objective)
            top = None
            for s in m.slo.get("slos", []):
                for burn in (s.get("burnRates") or {}).values():
                    if burn is not None and (top is None or burn > top):
                        top = burn
            slo = {"worstBurn": top}
        fabric = None
        if m.routerd is not None:
            # compact front-tier row (full payload on the member's own
            # /router.json): ring occupancy is what the dashboard needs
            ring = m.routerd.get("ring") or {}
            fabric = {
                "members": ring.get("members"),
                "routable": ring.get("routable"),
                "size": ring.get("size"),
                "partitions": ring.get("partitions"),
            }
        rollout = None
        if m.rollout is not None and m.rollout.get("stage") != "idle":
            # compact progressive-delivery row (full decision trail on
            # the router's own /rollout.json): stage + judge verdict is
            # what the fleet dashboard steers by
            judge = m.rollout.get("judge") or {}
            shadow = m.rollout.get("shadow") or {}
            trail = m.rollout.get("trail") or []
            rollout = {
                "stage": m.rollout.get("stage"),
                "generation": m.rollout.get("generation"),
                "candidateInstance": m.rollout.get("candidateInstance"),
                "incumbentInstance": m.rollout.get("incumbentInstance"),
                "lastVerdict": judge.get("lastVerdict"),
                "shadowSamples": shadow.get("samples"),
                "mismatchRate": shadow.get("mismatchRate"),
                "canaryRequests": (
                    (m.rollout.get("canary") or {}).get("requests")
                ),
                "lastTransition": trail[-1] if trail else None,
            }
        return {
            "member": m.name,
            "url": m.url,
            "status": m.status(self.stale_after_s, self.down_after_s),
            "role": m.role(),
            "ready": m.ready,
            "scrapeAgeSeconds": round(age, 3) if age is not None else None,
            "scrapes": m.attempts,
            "scrapeErrors": m.errors,
            "lastError": m.last_error,
            "slo": slo,
            "training": training,
            "devices": devices,
            "router": fabric,
            "rollout": rollout,
        }

    def _devices_rollup(self) -> dict:
        """Fleet-wide device memory view (ISSUE 17): per-member bytes,
        headroom and per-device rows — the eviction-policy input of
        ROADMAP item 6 (shed the member with the least headroom)."""
        per_member = {}
        tightest = None
        for m in self._members:
            if m.device is None:
                continue
            rows = m.device.get("devices") or []
            entry = {
                "mode": m.device.get("mode"),
                "bytesInUse": sum(
                    int(r.get("bytesInUse") or 0) for r in rows
                ),
                "budgetBytes": m.device.get("budgetBytes"),
                "headroomBytes": m.device.get("headroomBytes"),
                "generation": m.device.get("generation"),
                "devices": [
                    {
                        "device": r.get("device"),
                        "bytesInUse": r.get("bytesInUse"),
                        "peakBytes": r.get("peakBytes"),
                        "limitBytes": r.get("limitBytes"),
                    }
                    for r in rows
                ],
            }
            per_member[m.name] = entry
            head = entry["headroomBytes"]
            if head is not None and (
                tightest is None or head < tightest["headroomBytes"]
            ):
                tightest = {"member": m.name, "headroomBytes": head}
        return {"members": per_member, "tightest": tightest}

    def _slo_rollup(self) -> dict:
        """Worst burn rate per objective name across members: the router
        sheds away from whichever replica burns budget fastest."""
        worst: Dict[str, dict] = {}
        for m in self._members:
            for s in (m.slo or {}).get("slos", []):
                name = s.get("name")
                if not name:
                    continue
                burns = s.get("burnRates") or {}
                top_window, top_burn = None, None
                for window, burn in burns.items():
                    if burn is None:
                        continue
                    if top_burn is None or burn > top_burn:
                        top_window, top_burn = window, burn
                if top_burn is None:
                    continue
                cur = worst.get(name)
                if cur is None or top_burn > cur["burn"]:
                    worst[name] = {
                        "member": m.name,
                        "burn": top_burn,
                        "window": top_window,
                        "objective": s.get("objective"),
                        "errorBudgetRemaining":
                            s.get("errorBudgetRemaining"),
                        "firing": [
                            a.get("severity")
                            for a in s.get("alerts", [])
                            if a.get("firing")
                        ],
                    }
        return {"worstBurn": worst}

    def _partlog_rollup(self) -> dict:
        """Partlog topology: per-leader per-partition committed bytes,
        per-follower acked/lag, and min-acked across followers (the
        fleet-wide durable floor the router can read)."""
        leaders = []
        for m in self._members:
            topo = m.storage
            if not topo or topo.get("backend") != "partlog":
                continue
            if topo.get("role") not in (None, "leader"):
                continue
            repl = topo.get("replication") or {}
            followers = repl.get("followers") or []
            parts = []
            for detail in topo.get("partition_detail", []):
                k = str(detail.get("partition"))
                committed = detail.get("committed_bytes", 0)
                f_rows = []
                acked_vals = []
                for f in followers:
                    acked = (f.get("acked") or {}).get(k)
                    lag = (
                        max(committed - acked, 0)
                        if acked is not None else None
                    )
                    if acked is not None:
                        acked_vals.append(acked)
                    f_rows.append({
                        "follower": f.get("follower"),
                        "connected": f.get("connected"),
                        "ackedBytes": acked,
                        "lagBytes": lag,
                    })
                parts.append({
                    "partition": detail.get("partition"),
                    "committedBytes": committed,
                    "minAckedBytes":
                        min(acked_vals) if acked_vals else None,
                    "followers": f_rows,
                })
            leaders.append({
                "member": m.name,
                "partitions": topo.get("partitions"),
                "durability": topo.get("durability"),
                "minAcks": repl.get("min_acks"),
                "replicas": repl.get("replicas"),
                "partitionDetail": parts,
            })
        return {"leaders": leaders}

    def _placement(self) -> List[dict]:
        """Which member holds which engine bytes, and how: device
        resident, mesh sharded, or host mirror."""
        out = []
        for m in self._members:
            st = m.stats
            if not st:
                continue
            res = st.get("residency") or {}
            shard = st.get("sharding") or {}
            mode = (
                "mesh" if shard.get("enabled")
                else "resident" if res.get("enabled")
                else "host"
            )
            entry = {
                "member": m.name,
                "mode": mode,
                "paramBytes": res.get("paramBytes", 0),
                "scorers": [
                    {
                        "name": sc.get("name"),
                        "paramBytes": sc.get("paramBytes"),
                        "sharded": sc.get("sharded"),
                        "retired": sc.get("retired"),
                    }
                    for sc in res.get("scorers", [])
                ],
            }
            if shard.get("enabled"):
                entry["sharding"] = shard
            if "worker" in st:
                entry["worker"] = st["worker"]
                entry["poolSize"] = st.get("poolSize")
            out.append(entry)
        return out
