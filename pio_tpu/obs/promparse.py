"""Small Prometheus text-format parser.

Shared by the test suite (round-tripping every ``/metrics`` endpoint),
``bench.py`` (server-side metric deltas embedded in the bench artifact)
and the dashboard's serving view. Parses the subset the exposition
spec defines for text format 0.0.4: ``# HELP``/``# TYPE`` comment lines
and ``name{labels} value`` samples with escaped label values, plus the
OpenMetrics-style exemplar suffix our histograms append to bucket lines
(``... 42 # {trace_id="query-7"} 0.0042``).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Optional, Tuple

LabelSet = FrozenSet[Tuple[str, str]]


class ParsedMetrics:
    """Samples keyed by (metric name, frozenset of label pairs)."""

    def __init__(self):
        self.samples: Dict[Tuple[str, LabelSet], float] = {}
        self.types: Dict[str, str] = {}
        self.helps: Dict[str, str] = {}
        #: exemplars keyed like samples: (exemplar labels, exemplar value)
        self.exemplars: Dict[
            Tuple[str, LabelSet], Tuple[LabelSet, Optional[float]]
        ] = {}

    def value(self, name: str, **labels) -> Optional[float]:
        return self.samples.get((name, frozenset(
            (k, str(v)) for k, v in labels.items()
        )))

    def exemplar(self, name: str, **labels
                 ) -> Optional[Tuple[Dict[str, str], Optional[float]]]:
        """The exemplar attached to one sample line (bucket lines carry
        them), as ``({label: value}, observed_value)`` — e.g.
        ``({"trace_id": "query-7"}, 0.0042)``."""
        got = self.exemplars.get((name, frozenset(
            (k, str(v)) for k, v in labels.items()
        )))
        if got is None:
            return None
        ls, v = got
        return dict(ls), v

    def family(self, name: str) -> Dict[LabelSet, float]:
        """Every sample of one metric name, keyed by label set."""
        return {
            ls: v for (n, ls), v in self.samples.items() if n == name
        }

    def histogram_buckets(self, name: str, **labels):
        """Sorted ``[(le_float, cumulative_count)]`` for one histogram
        cell (``le`` excluded from the matching labels)."""
        want = {(k, str(v)) for k, v in labels.items()}
        out = []
        for ls, v in self.family(name + "_bucket").items():
            d = dict(ls)
            le = d.pop("le", None)
            if le is None or set(d.items()) != want:
                continue
            out.append((float("inf") if le == "+Inf" else float(le), v))
        out.sort(key=lambda p: p[0])
        return out

    def histogram_quantile(self, name: str, q: float,
                           **labels) -> Optional[float]:
        """Bucket-interpolated quantile from an exposed histogram (the
        PromQL ``histogram_quantile`` estimate)."""
        buckets = self.histogram_buckets(name, **labels)
        if not buckets or buckets[-1][1] <= 0:
            return None
        total = buckets[-1][1]
        rank = q * total
        prev_le, prev_cum = 0.0, 0.0
        for le, cum in buckets:
            if cum >= rank:
                if le == float("inf"):
                    return prev_le
                c = cum - prev_cum
                frac = (rank - prev_cum) / c if c > 0 else 1.0
                return prev_le + (le - prev_le) * min(max(frac, 0.0), 1.0)
            prev_le, prev_cum = le, cum
        return prev_le


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(s: str) -> LabelSet:
    """``a="b",c="d"`` (already stripped of braces) → label set."""
    pairs = []
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq].strip().lstrip(",").strip()
        assert s[eq + 1] == '"', f"unquoted label value near {s[i:]!r}"
        j = eq + 2
        buf = []
        while s[j] != '"':
            if s[j] == "\\":
                buf.append(s[j:j + 2])
                j += 2
            else:
                buf.append(s[j])
                j += 1
        pairs.append((name, _unescape("".join(buf))))
        i = j + 1
    return frozenset(pairs)


def parse_prometheus_text(text: str) -> ParsedMetrics:
    out = ParsedMetrics()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "HELP":
                out.helps[parts[2]] = _unescape(parts[3])
            elif len(parts) >= 4 and parts[1] == "TYPE":
                out.types[parts[2]] = parts[3]
            continue
        # sample: name[{labels}] value [timestamp] [# {exemplar} value]
        exemplar = None
        if " # " in line:
            base, ex_str = line.split(" # ", 1)
            if ex_str.startswith("{") and "}" in ex_str:
                line = base.rstrip()
                ex_labels_str, ex_rest = ex_str[1:].split("}", 1)
                ex_parts = ex_rest.split()
                exemplar = (
                    _parse_labels(ex_labels_str),
                    float(ex_parts[0]) if ex_parts else None,
                )
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_str, rest = rest.rsplit("}", 1)
            labels = _parse_labels(labels_str)
        else:
            name, rest = line.split(None, 1)
            labels = frozenset()
        value_str = rest.split()[0]
        value = (
            float("inf") if value_str == "+Inf"
            else float("-inf") if value_str == "-Inf"
            else float(value_str)
        )
        out.samples[(name.strip(), labels)] = value
        if exemplar is not None:
            out.exemplars[(name.strip(), labels)] = exemplar
    return out
