"""Small Prometheus text-format parser (and re-renderer).

Shared by the test suite (round-tripping every ``/metrics`` endpoint),
``bench.py`` (server-side metric deltas embedded in the bench artifact),
the dashboard's serving view, and the fleet aggregator (ISSUE 11), which
parses every member's scrape, relabels it with ``pio_tpu_member``, merges
and re-exposes the union. Parses the subset the exposition spec defines
for text format 0.0.4: ``# HELP``/``# TYPE`` comment lines and
``name{labels} value`` samples with escaped label values, plus the
OpenMetrics-style exemplar suffix our histograms append to bucket lines
(``... 42 # {trace_id="query-7"} 0.0042``).

Federation helpers:

- ``merge(*scrapes)`` — counters (and histogram series) sum, gauges are
  last-write-wins, conflicting ``# TYPE`` declarations raise;
- ``with_labels(pm, member=...)`` — inject a label into every sample;
- ``render(pm)`` — back to exposition text, round-trip-stable through
  ``parse_prometheus_text`` (exemplars included).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

LabelSet = FrozenSet[Tuple[str, str]]

#: suffixes that belong to a histogram/summary family rather than being
#: metric names of their own
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


class ParsedMetrics:
    """Samples keyed by (metric name, frozenset of label pairs)."""

    def __init__(self):
        self.samples: Dict[Tuple[str, LabelSet], float] = {}
        self.types: Dict[str, str] = {}
        self.helps: Dict[str, str] = {}
        #: exemplars keyed like samples: (exemplar labels, exemplar value)
        self.exemplars: Dict[
            Tuple[str, LabelSet], Tuple[LabelSet, Optional[float]]
        ] = {}

    def value(self, name: str, **labels) -> Optional[float]:
        return self.samples.get((name, frozenset(
            (k, str(v)) for k, v in labels.items()
        )))

    def exemplar(self, name: str, **labels
                 ) -> Optional[Tuple[Dict[str, str], Optional[float]]]:
        """The exemplar attached to one sample line (bucket lines carry
        them), as ``({label: value}, observed_value)`` — e.g.
        ``({"trace_id": "query-7"}, 0.0042)``."""
        got = self.exemplars.get((name, frozenset(
            (k, str(v)) for k, v in labels.items()
        )))
        if got is None:
            return None
        ls, v = got
        return dict(ls), v

    def family(self, name: str) -> Dict[LabelSet, float]:
        """Every sample of one metric name, keyed by label set."""
        return {
            ls: v for (n, ls), v in self.samples.items() if n == name
        }

    def histogram_buckets(self, name: str, **labels):
        """Sorted ``[(le_float, cumulative_count)]`` for one histogram
        cell (``le`` excluded from the matching labels)."""
        want = {(k, str(v)) for k, v in labels.items()}
        out = []
        for ls, v in self.family(name + "_bucket").items():
            d = dict(ls)
            le = d.pop("le", None)
            if le is None or set(d.items()) != want:
                continue
            out.append((float("inf") if le == "+Inf" else float(le), v))
        out.sort(key=lambda p: p[0])
        return out

    def histogram_quantile(self, name: str, q: float,
                           **labels) -> Optional[float]:
        """Bucket-interpolated quantile from an exposed histogram (the
        PromQL ``histogram_quantile`` estimate)."""
        buckets = self.histogram_buckets(name, **labels)
        if not buckets or buckets[-1][1] <= 0:
            return None
        total = buckets[-1][1]
        rank = q * total
        prev_le, prev_cum = 0.0, 0.0
        for le, cum in buckets:
            if cum >= rank:
                if le == float("inf"):
                    return prev_le
                c = cum - prev_cum
                frac = (rank - prev_cum) / c if c > 0 else 1.0
                return prev_le + (le - prev_le) * min(max(frac, 0.0), 1.0)
            prev_le, prev_cum = le, cum
        return prev_le


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(s: str) -> LabelSet:
    """``a="b",c="d"`` (already stripped of braces) → label set."""
    pairs = []
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq].strip().lstrip(",").strip()
        assert s[eq + 1] == '"', f"unquoted label value near {s[i:]!r}"
        j = eq + 2
        buf = []
        while s[j] != '"':
            if s[j] == "\\":
                buf.append(s[j:j + 2])
                j += 2
            else:
                buf.append(s[j])
                j += 1
        pairs.append((name, _unescape("".join(buf))))
        i = j + 1
    return frozenset(pairs)


def parse_prometheus_text(text: str) -> ParsedMetrics:
    out = ParsedMetrics()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "HELP":
                out.helps[parts[2]] = _unescape(parts[3])
            elif len(parts) >= 4 and parts[1] == "TYPE":
                out.types[parts[2]] = parts[3]
            continue
        # sample: name[{labels}] value [timestamp] [# {exemplar} value]
        exemplar = None
        if " # " in line:
            base, ex_str = line.split(" # ", 1)
            if ex_str.startswith("{") and "}" in ex_str:
                line = base.rstrip()
                ex_labels_str, ex_rest = ex_str[1:].split("}", 1)
                ex_parts = ex_rest.split()
                exemplar = (
                    _parse_labels(ex_labels_str),
                    float(ex_parts[0]) if ex_parts else None,
                )
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_str, rest = rest.rsplit("}", 1)
            labels = _parse_labels(labels_str)
        else:
            name, rest = line.split(None, 1)
            labels = frozenset()
        value_str = rest.split()[0]
        value = (
            float("inf") if value_str == "+Inf"
            else float("-inf") if value_str == "-Inf"
            else float(value_str)
        )
        out.samples[(name.strip(), labels)] = value
        if exemplar is not None:
            out.exemplars[(name.strip(), labels)] = exemplar
    return out


# ---------------------------------------------------------------------------
# federation helpers (ISSUE 11)
# ---------------------------------------------------------------------------

def family_base(name: str, types: Dict[str, str]) -> str:
    """The family a sample line belongs to: ``foo_bucket``/``foo_sum``/
    ``foo_count`` collapse to ``foo`` when ``foo`` is a declared
    histogram or summary; every other name is its own family."""
    for suf in _FAMILY_SUFFIXES:
        if name.endswith(suf):
            base = name[: -len(suf)]
            if types.get(base) in ("histogram", "summary"):
                return base
    return name


def _merge_mode(name: str, types: Dict[str, str]) -> str:
    """``sum`` or ``last`` for one sample name under the merged types."""
    base = family_base(name, types)
    typ = types.get(base)
    if typ == "counter":
        return "sum"
    if typ in ("histogram", "summary"):
        # bucket/sum/count series are cumulative -> add; summary
        # quantile samples are point estimates -> last-write-wins
        if name != base or typ == "histogram":
            return "sum"
        return "last"
    if typ == "gauge":
        return "last"
    # untyped: counter naming discipline says *_total is cumulative
    return "sum" if name.endswith("_total") else "last"


def merge(*scrapes: ParsedMetrics) -> ParsedMetrics:
    """Merge scrapes into one: counter(-like) series sum, gauges are
    last-write-wins (later argument wins), histograms add bucket-wise
    (their ``_bucket``/``_sum``/``_count`` series are all cumulative).
    Exemplars are last-write-wins per sample. A family declared with
    two different ``# TYPE``\\ s across scrapes raises ``ValueError`` —
    silently summing a gauge into a counter would corrupt both."""
    out = ParsedMetrics()
    for pm in scrapes:
        for fam, typ in pm.types.items():
            prev = out.types.get(fam)
            if prev is not None and prev != typ:
                raise ValueError(
                    f"conflicting TYPE for {fam!r}: {prev!r} vs {typ!r}"
                )
            out.types[fam] = typ
        for fam, h in pm.helps.items():
            out.helps.setdefault(fam, h)
    for pm in scrapes:
        for key, v in pm.samples.items():
            if _merge_mode(key[0], out.types) == "sum":
                out.samples[key] = out.samples.get(key, 0.0) + v
            else:
                out.samples[key] = v
        out.exemplars.update(pm.exemplars)
    return out


def with_labels(pm: ParsedMetrics, **labels) -> ParsedMetrics:
    """A copy of ``pm`` with ``labels`` injected into every sample (the
    fleet aggregator stamps ``pio_tpu_member="host:port"`` this way).
    An injected name overrides any same-named label already present."""
    inj = tuple((k, str(v)) for k, v in labels.items())
    names = frozenset(k for k, _ in inj)

    def rekey(key):
        name, ls = key
        kept = tuple(p for p in ls if p[0] not in names)
        return name, frozenset(kept + inj)

    out = ParsedMetrics()
    out.types.update(pm.types)
    out.helps.update(pm.helps)
    out.samples = {rekey(k): v for k, v in pm.samples.items()}
    out.exemplars = {rekey(k): v for k, v in pm.exemplars.items()}
    return out


def _esc_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _sample_sort_key(name: str, ls: LabelSet):
    """Stable order: name, then labels (with ``le`` compared numerically
    last so histogram buckets render in ascending edge order)."""
    d = dict(ls)
    le = d.pop("le", None)
    le_v = (
        0.0 if le is None
        else float("inf") if le == "+Inf" else float(le)
    )
    return name, tuple(sorted(d.items())), le_v


def render(pm: ParsedMetrics) -> List[str]:
    """Exposition lines for ``pm`` — HELP/TYPE once per family, samples
    grouped under their family, exemplars re-attached. The output parses
    back to an equal ``ParsedMetrics`` (the round-trip property the unit
    tests pin down)."""
    fams: Dict[str, List[Tuple[str, LabelSet]]] = {}
    for (name, ls) in pm.samples:
        fams.setdefault(family_base(name, pm.types), []).append((name, ls))
    # families with only HELP/TYPE and no samples still render their head
    for fam in list(pm.types) + list(pm.helps):
        fams.setdefault(fam, [])
    lines: List[str] = []
    for fam in sorted(fams):
        if fam in pm.helps:
            h = pm.helps[fam].replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {fam} {h}")
        if fam in pm.types:
            lines.append(f"# TYPE {fam} {pm.types[fam]}")
        for name, ls in sorted(
            fams[fam], key=lambda p: _sample_sort_key(p[0], p[1])
        ):
            if ls:
                body = ",".join(
                    f'{k}="{_esc_label(v)}"' for k, v in sorted(ls)
                )
                head = f"{name}{{{body}}}"
            else:
                head = name
            line = f"{head} {_fmt_value(pm.samples[(name, ls)])}"
            ex = pm.exemplars.get((name, ls))
            if ex is not None:
                ex_ls, ex_v = ex
                ex_body = ",".join(
                    f'{k}="{_esc_label(v)}"' for k, v in sorted(ex_ls)
                )
                line += f" # {{{ex_body}}}"
                if ex_v is not None:
                    line += f" {_fmt_value(ex_v)}"
            lines.append(line)
    return lines
