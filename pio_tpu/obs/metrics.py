"""Metrics registry — Counter / Gauge / Histogram with Prometheus text
exposition.

No client-library dependency: the text format is simple enough to emit
directly, and owning the types lets the SO_REUSEPORT serving pool mirror
every cell into a shared-memory stripe (:mod:`pio_tpu.obs.shm`) so one
scrape reports pool-wide totals.

Conventions follow the Prometheus exposition spec:

- one ``# HELP``/``# TYPE`` pair per metric family, HELP text escaped
  (``\\`` and newline — label values additionally escape ``"``);
- histograms are CUMULATIVE fixed-bucket (``_bucket{le=...}`` rows
  monotone non-decreasing, closed by ``le="+Inf"``) with ``_sum`` and
  ``_count`` companions;
- cells (one per label-value combination) are created lazily via
  ``metric.labels(...)`` and registration is idempotent — asking the
  registry for an existing family returns it.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: THE monotonic duration clock (see pio_tpu/obs/__init__.py docstring).
monotonic_s = time.perf_counter

#: serving-latency histogram edges in SECONDS: 100 µs (host-mirror
#: scorer floor) through 10 s (cold XLA bucket compile on first query).
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def escape_help(text: str) -> str:
    """Escape HELP text per the Prometheus text format (backslash and
    newline only — quotes are legal in HELP)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value: str) -> str:
    """Escape a label value per the Prometheus text format."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v: float) -> str:
    """Sample-value formatting: integers without the trailing ``.0``."""
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 2 ** 53 else repr(f)


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Cell:
    """One (metric, label-values) combination: a locked local value with
    an optional shared-memory mirror (pool mode)."""

    __slots__ = ("_lock", "_v", "_seg", "_widx", "_slot")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0
        self._seg = None
        self._widx = None
        self._slot = None

    def _bind(self, seg, widx: int, slot: int) -> None:
        """Mirror into shm slot ``slot`` of worker stripe ``widx``. The
        stripe may already carry a value (a respawned worker re-binding
        its old stripe): adopt it so pool totals survive worker crashes."""
        with self._lock:
            self._v += seg.read(widx, slot)
            self._seg, self._widx, self._slot = seg, widx, slot
            seg.set(widx, slot, self._v)

    def _add(self, v: float) -> None:
        with self._lock:
            self._v += v
            if self._seg is not None:
                self._seg.set(self._widx, self._slot, self._v)

    def inc(self, v: float = 1.0) -> None:
        """Bound-cell fast path: per-request code resolves ``labels()``
        once at setup and bumps the cell directly — label-tuple
        stringification and the registry dict lookup cost more than the
        add itself on hot paths. Counter callers must keep v >= 0 (the
        family-level ``Counter.inc`` enforces it; this deliberately
        doesn't, so gauge cells can decrement)."""
        self._add(v)

    def _set(self, v: float) -> None:
        with self._lock:
            self._v = v
            if self._seg is not None:
                self._seg.set(self._widx, self._slot, self._v)

    @property
    def value(self) -> float:
        """Local (this-process) value."""
        return self._v

    def _pool_value(self) -> float:
        """Pool-wide value: sum of every worker's stripe when bound."""
        if self._seg is None:
            return self._v
        return self._seg.sum_slot(self._slot)


class _Metric:
    """Family base: name, help, label names, lazily created cells."""

    typ = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        #: label-values tuple -> cell, in creation order (dicts preserve
        #: insertion order — pool slot assignment depends on it)
        self._cells: Dict[Tuple[str, ...], object] = {}

    def _make_cell(self):
        return _Cell()

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}"
            )
        cell = self._cells.get(values)
        if cell is None:
            with self._lock:
                cell = self._cells.setdefault(values, self._make_cell())
        return cell

    def _default_cell(self):
        """The zero-label cell (for label-less families)."""
        return self.labels()

    def samples(self, pool: bool = True) -> List[str]:
        raise NotImplementedError

    def render(self, pool: bool = True) -> List[str]:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.typ}",
        ]
        lines.extend(self.samples(pool=pool))
        return lines


class _ScalarMetric(_Metric):
    def samples(self, pool: bool = True) -> List[str]:
        out = []
        for values, cell in list(self._cells.items()):
            v = cell._pool_value() if pool else cell.value
            out.append(
                f"{self.name}{_label_str(self.labelnames, values)} {_fmt(v)}"
            )
        return out


class Counter(_ScalarMetric):
    typ = "counter"

    def inc(self, v: float = 1.0, **labels) -> None:
        if v < 0:
            raise ValueError("counters only go up")
        (self.labels(**labels) if labels else self._default_cell())._add(v)

    def value(self, *values) -> float:
        """Pool-wide value of one cell (local value if unbound)."""
        return self.labels(*values)._pool_value()


class Gauge(_ScalarMetric):
    """Gauges stay LOCAL in pool mode — summing one worker's pool-size
    or uptime gauge across stripes would be nonsense, so the registry
    never binds them to the shared segment."""

    typ = "gauge"

    def set(self, v: float, **labels) -> None:
        (self.labels(**labels) if labels else self._default_cell())._set(v)

    def inc(self, v: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels else self._default_cell())._add(v)

    def value(self, *values) -> float:
        return self.labels(*values).value


class _HistogramCell:
    """Fixed cumulative buckets + sum + count, with optional shm mirror
    (buckets, sum and count each take one slot).

    Each bucket also remembers the most recent *exemplar* — a trace id
    and the observed value — so the text exposition can point at a
    concrete ``/traces.json`` entry per latency band. Exemplars are
    strings and stay LOCAL (the shm stripe is float64-only); in pool
    mode each worker exposes its own."""

    __slots__ = ("_lock", "_edges", "_buckets", "_sum", "_count",
                 "_seg", "_widx", "_slot0", "_exemplars")

    def __init__(self, edges: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._edges = edges  # finite upper bounds, sorted
        self._buckets = [0] * (len(edges) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._seg = None
        self._widx = None
        self._slot0 = None
        self._exemplars: Dict[int, Tuple[str, float]] = {}  # idx -> (id, v)

    def n_slots(self) -> int:
        return len(self._buckets) + 2  # buckets + sum + count

    def _bind(self, seg, widx: int, slot0: int) -> None:
        with self._lock:
            nb = len(self._buckets)
            for k in range(nb):
                self._buckets[k] += int(seg.read(widx, slot0 + k))
            self._sum += seg.read(widx, slot0 + nb)
            self._count += int(seg.read(widx, slot0 + nb + 1))
            self._seg, self._widx, self._slot0 = seg, widx, slot0
            self._mirror_locked()

    def _mirror_locked(self) -> None:
        nb = len(self._buckets)
        for k, c in enumerate(self._buckets):
            self._seg.set(self._widx, self._slot0 + k, float(c))
        self._seg.set(self._widx, self._slot0 + nb, self._sum)
        self._seg.set(self._widx, self._slot0 + nb + 1, float(self._count))

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        idx = bisect.bisect_left(self._edges, v)
        with self._lock:
            self._buckets[idx] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                self._exemplars[idx] = (str(exemplar), v)
            if self._seg is not None:
                nb = len(self._buckets)
                self._seg.set(
                    self._widx, self._slot0 + idx, float(self._buckets[idx])
                )
                self._seg.set(self._widx, self._slot0 + nb, self._sum)
                self._seg.set(
                    self._widx, self._slot0 + nb + 1, float(self._count)
                )

    def _exemplar_snapshot(self) -> Dict[int, Tuple[str, float]]:
        with self._lock:
            return dict(self._exemplars)

    def _snapshot(self, pool: bool) -> Tuple[List[int], float, int]:
        if pool and self._seg is not None:
            nb = len(self._buckets)
            buckets = [
                int(self._seg.sum_slot(self._slot0 + k)) for k in range(nb)
            ]
            return (
                buckets,
                self._seg.sum_slot(self._slot0 + nb),
                int(self._seg.sum_slot(self._slot0 + nb + 1)),
            )
        with self._lock:
            return list(self._buckets), self._sum, self._count

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def count_le(self, threshold: float,
                 pool: bool = True) -> Tuple[int, int]:
        """``(observations ≤ threshold, total observations)`` — the
        good/total pair a latency SLO needs. The threshold snaps DOWN to
        the nearest bucket edge (cumulative buckets can't see inside a
        bucket; snapping down undercounts "good", never overcounts), so
        declare SLO thresholds on bucket edges. Pool-wide when bound."""
        buckets, _sum, count = self._snapshot(pool)
        k = bisect.bisect_right(self._edges, threshold)
        return sum(buckets[:k]), count

    def quantile(self, q: float, pool: bool = False) -> Optional[float]:
        """Bucket-interpolated quantile estimate (linear within the
        winning bucket; the +Inf bucket clamps to its lower edge)."""
        buckets, _sum, count = self._snapshot(pool)
        if count == 0:
            return None
        rank = q * count
        cum = 0
        for k, c in enumerate(buckets):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                lo = self._edges[k - 1] if k > 0 else 0.0
                if k >= len(self._edges):  # +Inf bucket
                    return self._edges[-1] if self._edges else lo
                hi = self._edges[k]
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self._edges[-1] if self._edges else None


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, help, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        edges = tuple(sorted(float(b) for b in buckets
                             if b != float("inf")))
        if not edges:
            raise ValueError("histogram needs at least one finite bucket")
        self.buckets = edges

    def _make_cell(self):
        return _HistogramCell(self.buckets)

    def observe(self, v: float, exemplar: Optional[str] = None,
                **labels) -> None:
        (self.labels(**labels) if labels
         else self._default_cell()).observe(v, exemplar=exemplar)

    def quantile(self, q: float, pool: bool = False) -> Optional[float]:
        """Family-wide bucket-interpolated quantile: cells merge
        bucket-wise first (all cells share this family's edges), so a
        labeled histogram still answers "p95 across every label set" —
        bench reads ``pio_tpu_repl_ack_seconds`` this way now that it
        is per-partition/per-follower."""
        with self._lock:
            cells = list(self._cells.values())
        if not cells:
            return None
        merged = _HistogramCell(self.buckets)
        for cell in cells:
            buckets, sum_, count = cell._snapshot(pool)
            for k, c in enumerate(buckets):
                merged._buckets[k] += c
            merged._sum += sum_
            merged._count += count
        return merged.quantile(q, pool=False)

    def samples(self, pool: bool = True) -> List[str]:
        out = []
        for values, cell in list(self._cells.items()):
            buckets, sum_, count = cell._snapshot(pool)
            exemplars = cell._exemplar_snapshot()
            cum = 0
            for k, (edge, c) in enumerate(zip(self._edge_strs(), buckets)):
                cum += c
                ls = _label_str(
                    self.labelnames + ("le",), values + (edge,)
                )
                line = f"{self.name}_bucket{ls} {cum}"
                ex = exemplars.get(k)
                if ex is not None:
                    # OpenMetrics-style exemplar: the most recent trace
                    # id observed into THIS bucket (non-cumulative)
                    eid, ev = ex
                    line += (
                        f' # {{trace_id="{escape_label_value(eid)}"}}'
                        f" {_fmt(ev)}"
                    )
                out.append(line)
            base = _label_str(self.labelnames, values)
            out.append(f"{self.name}_sum{base} {_fmt(sum_)}")
            out.append(f"{self.name}_count{base} {count}")
        return out

    def _edge_strs(self) -> List[str]:
        return [_fmt(e) for e in self.buckets] + ["+Inf"]


class RequestWindow:
    """Cumulative request stats plus a bounded ring of timestamped
    samples for ``?window=`` recent views.

    Replaces the query server's private ``_LatencyStats``: cumulative
    count/errors/sum stay exact forever; percentiles for a recent window
    come from the ring (the CUMULATIVE percentiles in ``/stats.json``
    come from the latency histogram instead — see the server handlers)."""

    def __init__(self, cap: int = 8192):
        self._lock = threading.Lock()
        self._cap = cap
        self._ring: List[Tuple[float, float, bool]] = []  # (t, ms, error)
        self._pos = 0
        self.count = 0
        self.errors = 0
        self.total_ms = 0.0

    def record(self, ms: float, error: bool = False) -> None:
        with self._lock:
            self.count += 1
            if error:
                self.errors += 1
            self.total_ms += ms
            item = (monotonic_s(), ms, error)
            if len(self._ring) < self._cap:
                self._ring.append(item)
            else:
                self._ring[self._pos] = item
                self._pos = (self._pos + 1) % self._cap

    # pio: endpoint=/stats.json
    def to_dict(self) -> dict:
        """The classic ``/stats.json`` shape: exact cumulative count/
        errors/avg, percentiles over the ring (recent ``cap`` requests)."""
        with self._lock:
            xs = sorted(ms for _, ms, _ in self._ring)
            count, errors, total = self.count, self.errors, self.total_ms
        n = len(xs)
        q = lambda f: round(xs[min(int(f * n), n - 1)], 3) if n else None
        return {
            "requestCount": count,
            "errorCount": errors,
            "avgMs": round(total / count, 3) if count else None,
            "p50Ms": q(0.50),
            "p95Ms": q(0.95),
            "p99Ms": q(0.99),
        }

    # pio: endpoint=/stats.json
    def window(self, window_s: float) -> dict:
        """count/errors/avg/p50/p95/p99 over the trailing ``window_s``
        seconds (best effort: bounded by the ring capacity)."""
        cutoff = monotonic_s() - window_s
        with self._lock:
            xs = [(ms, err) for t, ms, err in self._ring if t >= cutoff]
        xs.sort(key=lambda p: p[0])
        n = len(xs)
        q = lambda f: xs[min(int(f * n), n - 1)][0] if n else None
        return {
            "windowSeconds": window_s,
            "requestCount": n,
            "errorCount": sum(1 for _, err in xs if err),
            "avgMs": (sum(ms for ms, _ in xs) / n) if n else None,
            "p50Ms": q(0.50),
            "p95Ms": q(0.95),
            "p99Ms": q(0.99),
        }


class MetricsRegistry:
    """Ordered family registry with pool-segment binding and pluggable
    extra-line collectors (e.g. computed quantile summaries)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], List[str]]] = []
        self._segment = None
        self._worker_idx: Optional[int] = None

    # -- registration ------------------------------------------------------
    def _register(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type or label set"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help, labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help, labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help, labelnames=(),
                  buckets=DEFAULT_LATENCY_BUCKETS) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def add_collector(self, fn: Callable[[], List[str]]) -> None:
        """Append a callable producing extra exposition lines (rendered
        after the registered families; the callable owns its HELP/TYPE)."""
        self._collectors.append(fn)

    # -- pool mode ---------------------------------------------------------
    def bind_pool_segment(self, segment, worker_idx: int) -> None:
        """Mirror every *currently registered* counter/histogram cell
        into the worker's stripe of ``segment``.

        Slot assignment is by registration order, so every pool worker —
        running identical service-init code — computes the same layout.
        Cells created AFTER binding (e.g. dynamically labelled) stay
        local-only; pool metrics must therefore be declared up front
        (the serving services pre-create their stage cells in
        ``__init__``). Gauges are never bound (summing them across
        workers is meaningless)."""
        with self._lock:
            self._segment = segment
            self._worker_idx = worker_idx
            slot = 0
            for m in self._metrics.values():
                if isinstance(m, Gauge):
                    continue
                for cell in m._cells.values():
                    need = (
                        cell.n_slots()
                        if isinstance(cell, _HistogramCell) else 1
                    )
                    if slot + need > segment.slots_per_worker:
                        raise ValueError(
                            f"pool metrics segment too small: need > "
                            f"{segment.slots_per_worker} slots"
                        )
                    cell._bind(segment, worker_idx, slot)
                    slot += need

    @property
    def pool_bound(self) -> bool:
        return self._segment is not None

    # -- exposition --------------------------------------------------------
    def render_prefixed(self, prefixes, pool: bool = True) -> List[str]:
        """Exposition lines for just the families whose name starts with
        one of ``prefixes``. Serving daemons keep per-instance registries
        but the storage layer's families (group commit, the partitioned
        log and its replication links) live on the process-global
        registry; this is the bridge a daemon adds as a collector to
        surface a chosen slice of them on its own ``/metrics``."""
        pfx = tuple(prefixes)
        with self._lock:
            metrics = [
                m for m in self._metrics.values() if m.name.startswith(pfx)
            ]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render(pool=pool))
        return lines

    def render(self, pool: bool = True) -> List[str]:
        """Exposition lines for every family (pool-wide values for bound
        cells when ``pool``) plus collector extras."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        for m in metrics:
            lines.extend(m.render(pool=pool))
        for fn in collectors:
            try:
                lines.extend(fn())
            except Exception:  # a broken collector must not kill /metrics
                pass
        return lines


#: process-wide default registry — used by layers with no natural owner
#: (storage group commit, training workflow). HTTP services create their
#: own registry per service instance so embedded/test servers don't
#: bleed counters into each other.
REGISTRY = MetricsRegistry()
