"""SLO objectives and multi-window burn-rate evaluation.

Turns the cumulative counters/histograms the obs registry already
maintains into the operator-facing question: *are we meeting our service
level objective, and how fast are we burning the error budget?* No
external dependency — the same arithmetic Prometheus alert rules would
run, executed in-process and surfaced as ``GET /slo.json`` plus
``pio_tpu_slo_*`` gauges.

**Objectives** are declared as compact specs (the ``pio deploy --slo``
syntax)::

    p99=50ms:99.9        # 99.9% of requests complete within 50 ms
    p95=25ms:99/6h       # 99% within 25 ms, budgeted over a 6 h window
    availability=99.95   # 99.95% of requests succeed

Latency objectives read good/total straight from histogram buckets
(``count_le`` — the threshold snaps to a bucket edge), availability from
the request/error counters; in pool mode both are pool-wide for free
because the underlying cells are shared-memory bound.

**Burn rate** over a trailing window ``w`` is ``error_rate(w) /
(1 - objective)`` — 1.0 means the budget exactly lasts the SLO window,
14.4 means a 30-day budget gone in ~2 days. Alerting uses the classic
multi-window fast/slow pairs (Google SRE workbook ch. 5): a *page* needs
BOTH the 5 m and 1 h windows above 14.4 (fast response, but the long
window de-flaps it); a *ticket* needs 30 m and 6 h above 6. Windowed
rates come from a ring of (t, good, total) snapshots taken at each
evaluation — the same scrape-driven sampling model Prometheus uses.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from pio_tpu.obs.metrics import MetricsRegistry, monotonic_s

#: ((fast_window_s, slow_window_s, burn_threshold, severity), ...)
DEFAULT_BURN_WINDOWS: Tuple[Tuple[float, float, float, str], ...] = (
    (300.0, 3600.0, 14.4, "page"),
    (1800.0, 21600.0, 6.0, "ticket"),
)

_DUR_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0,
              "h": 3600.0, "d": 86400.0}

_SPEC_RE = re.compile(
    r"^(?P<name>[a-zA-Z][\w.-]*)"
    r"(?:=(?P<threshold>[0-9.]+(?:us|ms|s))?)?"
    r"(?::|=)(?P<objective>[0-9.]+)"
    r"(?:/(?P<window>[0-9.]+(?:s|m|h|d)))?$"
)


def parse_duration_s(text: str) -> float:
    m = re.match(r"^([0-9.]+)(us|ms|s|m|h|d)$", text.strip())
    if not m:
        raise ValueError(f"cannot parse duration {text!r} (want e.g. 50ms)")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declared objective: ``objective`` is a FRACTION (0.999 for
    three nines); ``threshold_s`` set only for latency objectives;
    ``window_s`` is the error-budget period."""

    name: str
    kind: str  # "latency" | "availability"
    objective: float
    threshold_s: Optional[float] = None
    window_s: float = 3600.0

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO objective must be in (0, 1), got {self.objective}"
            )
        if self.kind == "latency" and self.threshold_s is None:
            raise ValueError("latency SLO needs a threshold")

    @property
    def budget(self) -> float:
        """Allowed error fraction (1 - objective)."""
        return 1.0 - self.objective


def parse_slo(spec: str) -> SLObjective:
    """``p99=50ms:99.9[/6h]`` / ``availability=99.9[/6h]`` → objective.

    The left-hand name is free-form (``p99`` is a label, the math is
    "fraction of requests within the threshold"); a spec with a duration
    is a latency objective, one without is availability. The objective
    is a PERCENT (99.9 → 0.999)."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"cannot parse SLO spec {spec!r} "
            f"(want p99=50ms:99.9 or availability=99.9, optional /6h)"
        )
    name = m.group("name")
    pct = float(m.group("objective"))
    if not 0.0 < pct < 100.0:
        raise ValueError(f"SLO objective percent out of range: {pct}")
    window_s = (
        parse_duration_s(m.group("window")) if m.group("window") else 3600.0
    )
    if m.group("threshold"):
        return SLObjective(
            name=f"latency_{name.lower()}",
            kind="latency",
            objective=pct / 100.0,
            threshold_s=parse_duration_s(m.group("threshold")),
            window_s=window_s,
        )
    if name.lower() in ("availability", "avail", "errors", "success"):
        return SLObjective(
            name="availability",
            kind="availability",
            objective=pct / 100.0,
            window_s=window_s,
        )
    raise ValueError(
        f"SLO spec {spec!r} has no latency threshold and is not an "
        f"availability objective"
    )


class _Series:
    """One objective + its cumulative source + snapshot history."""

    __slots__ = ("slo", "good_total", "history", "cap")

    def __init__(self, slo: SLObjective,
                 good_total: Callable[[], Tuple[float, float]],
                 cap: int = 2048):
        self.slo = slo
        self.good_total = good_total
        #: (t, good, total) snapshots, chronological, bounded
        self.history: List[Tuple[float, float, float]] = []
        self.cap = cap

    def sample(self, now: float) -> Tuple[float, float]:
        good, total = self.good_total()
        self.history.append((now, float(good), float(total)))
        if len(self.history) > self.cap:
            # drop the oldest half in one slice (amortized O(1))
            del self.history[: self.cap // 2]
        return float(good), float(total)

    def window_delta(self, now: float,
                     window_s: float) -> Tuple[float, float, float]:
        """(bad, total, actual_span_s) over the trailing window — the
        newest snapshot at least ``window_s`` old anchors the delta; with
        less history, the oldest snapshot does (Prometheus ``rate`` over
        a short range behaves the same way)."""
        if not self.history:
            return 0.0, 0.0, 0.0
        cutoff = now - window_s
        anchor = self.history[0]
        for snap in reversed(self.history):
            if snap[0] <= cutoff:
                anchor = snap
                break
        head = self.history[-1]
        d_total = max(head[2] - anchor[2], 0.0)
        d_good = max(head[1] - anchor[1], 0.0)
        return max(d_total - d_good, 0.0), d_total, head[0] - anchor[0]


class SLOEngine:
    """Evaluates declared objectives against live cumulative sources.

    ``registry`` (optional) receives ``pio_tpu_slo_error_budget_remaining
    {slo}`` and ``pio_tpu_slo_burn_rate{slo,window}`` gauges, refreshed on
    every :meth:`evaluate` — so a plain ``/metrics`` scrape carries the
    SLO state even if nothing ever polls ``/slo.json``.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 burn_windows: Sequence[Tuple[float, float, float, str]] =
                 DEFAULT_BURN_WINDOWS):
        self._lock = threading.Lock()
        self._series: List[_Series] = []
        self.burn_windows = tuple(burn_windows)
        self._budget_gauge = None
        self._burn_gauge = None
        if registry is not None:
            self._budget_gauge = registry.gauge(
                "pio_tpu_slo_error_budget_remaining",
                "Fraction of the SLO error budget left over the SLO "
                "window (1 = untouched, <0 = overspent)",
                ("slo",),
            )
            self._burn_gauge = registry.gauge(
                "pio_tpu_slo_burn_rate",
                "Error-budget burn rate over a trailing window "
                "(1 = budget exactly lasts the SLO window)",
                ("slo", "window"),
            )

    def add(self, slo: SLObjective,
            good_total: Callable[[], Tuple[float, float]]) -> None:
        """Register an objective with its cumulative (good, total)
        source. Sources must be monotone non-decreasing (counters)."""
        with self._lock:
            self._series.append(_Series(slo, good_total))

    def __len__(self) -> int:
        return len(self._series)

    @property
    def objectives(self) -> List[SLObjective]:
        with self._lock:
            return [s.slo for s in self._series]

    def sample(self, now: Optional[float] = None) -> None:
        """Take one snapshot of every source (tests drive this with an
        explicit clock to build deterministic histories)."""
        t = monotonic_s() if now is None else now
        with self._lock:
            for s in self._series:
                s.sample(t)

    def _window_set(self, slo: SLObjective) -> List[float]:
        ws = {w for pair in self.burn_windows for w in pair[:2]}
        ws.add(slo.window_s)
        return sorted(ws)

    # pio: endpoint=/slo.json
    def evaluate(self, now: Optional[float] = None,
                 take_sample: bool = True) -> dict:
        """The ``GET /slo.json`` body: per objective, cumulative totals,
        remaining error budget over the SLO window, burn rate per
        trailing window, and which multi-window alerts fire."""
        t = monotonic_s() if now is None else now
        with self._lock:
            series = list(self._series)
        out = []
        for s in series:
            if take_sample:
                s.sample(t)
            slo = s.slo
            head = s.history[-1] if s.history else (t, 0.0, 0.0)
            total, good = head[2], head[1]
            burns: Dict[str, float] = {}
            burn_by_w: Dict[float, float] = {}
            for w in self._window_set(slo):
                bad_w, total_w, _span = s.window_delta(t, w)
                rate = (bad_w / total_w) if total_w > 0 else 0.0
                burn = rate / slo.budget
                burn_by_w[w] = burn
                burns[f"{int(w)}s"] = round(burn, 4)
            # budget remaining over the SLO window
            bad_slo, total_slo, _ = s.window_delta(t, slo.window_s)
            allowed = slo.budget * total_slo
            remaining = (
                1.0 - (bad_slo / allowed) if allowed > 0 else 1.0
            )
            alerts = []
            for fast, slow, threshold, severity in self.burn_windows:
                firing = (
                    burn_by_w.get(fast, 0.0) > threshold
                    and burn_by_w.get(slow, 0.0) > threshold
                )
                alerts.append({
                    "severity": severity,
                    "fastWindowS": fast,
                    "slowWindowS": slow,
                    "burnThreshold": threshold,
                    "firing": firing,
                })
            entry = {
                "name": slo.name,
                "kind": slo.kind,
                "objective": slo.objective,
                "windowSeconds": slo.window_s,
                "total": total,
                "errors": max(total - good, 0.0),
                "errorBudgetRemaining": round(remaining, 4),
                "burnRates": burns,
                "alerts": alerts,
            }
            if slo.threshold_s is not None:
                entry["thresholdMs"] = round(slo.threshold_s * 1e3, 3)
            out.append(entry)
            if self._budget_gauge is not None:
                self._budget_gauge.set(remaining, slo=slo.name)
            if self._burn_gauge is not None:
                for w, b in burn_by_w.items():
                    self._burn_gauge.set(b, slo=slo.name, window=f"{int(w)}s")
        return {"slos": out}


def engine_for_specs(
    specs: Sequence[str],
    registry: MetricsRegistry,
    availability_source: Callable[[], Tuple[float, float]],
    latency_cell_getter: Callable[[], object],
) -> SLOEngine:
    """Wire parsed specs to a serving service's sources: availability
    objectives read the request/error counters, latency objectives read
    ``count_le`` off the full-request latency histogram cell."""
    eng = SLOEngine(registry=registry)
    for spec in specs:
        slo = parse_slo(spec) if isinstance(spec, str) else spec
        if slo.kind == "availability":
            eng.add(slo, availability_source)
        else:
            threshold = slo.threshold_s

            def good_total(threshold=threshold):
                cell = latency_cell_getter()
                if cell is None:
                    return 0.0, 0.0
                return cell.count_le(threshold, pool=True)

            eng.add(slo, good_total)
    return eng
