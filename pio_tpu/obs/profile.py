"""Opt-in JAX profiler hook for serving: ``PIO_TPU_PROFILE=dir``.

Training already supports ``--profile-dir`` (a trace of the whole run);
serving needs something narrower — profiling every query forever would
drown the trace and tax the hot path. This hook captures ONE
``jax.profiler`` trace covering the first N device executions after
deploy (N from ``PIO_TPU_PROFILE_EXECUTIONS``, default 8: enough to see
both the bucket-compile execution and warm steady-state dispatches),
then gets out of the way. On a long-lived deploy the interesting window
is rarely the first N executions, so the hook can be re-armed at
runtime: :meth:`DeviceProfileHook.restart` rotates the output into a
numbered subdirectory (``capture-0001`` …) and captures the NEXT N
executions — exposed as ``POST /debug/profile.json?restart=1`` on the
query server. View with tensorboard/xprof.
"""

from __future__ import annotations

import logging
import os
import threading
from contextlib import contextmanager

from pio_tpu.utils import knobs

log = logging.getLogger("pio_tpu.obs")

ENV_DIR = "PIO_TPU_PROFILE"
ENV_N = "PIO_TPU_PROFILE_EXECUTIONS"


class DeviceProfileHook:
    """Context manager factory wrapped around the device-execute stage.

    Inert (zero overhead beyond one attribute check) unless constructed
    with a directory — the serving services build it from the
    environment via :func:`from_env`.
    """

    def __init__(self, directory: str = "", first_n: int = 8):
        self.directory = directory
        self.first_n = first_n
        self._lock = threading.Lock()
        self._seen = 0
        self._active = False
        self._done = not directory
        self._captures = 0  # completed/aborted capture windows

    @classmethod
    def from_env(cls) -> "DeviceProfileHook":

        directory = knobs.knob_str(ENV_DIR)
        return cls(directory, knobs.knob_int(ENV_N))

    @property
    def enabled(self) -> bool:
        return bool(self.directory) and not self._done

    def to_dict(self) -> dict:
        """Status for ``GET /debug/profile.json``."""
        with self._lock:
            return {
                "configured": bool(self.directory),
                "directory": self.directory,
                "firstN": self.first_n,
                "seen": self._seen,
                "active": self._active,
                "armed": bool(self.directory) and not self._done,
                "captures": self._captures,
            }

    def restart(self, first_n: int = 0) -> dict:
        """Re-arm for the next ``first_n`` (default: the configured N)
        device executions, rotating output into a fresh numbered
        subdirectory so earlier captures survive. Safe while a capture
        is mid-flight — the active trace is stopped first."""
        with self._lock:
            if not self.directory:
                return {"restarted": False,
                        "message": f"{ENV_DIR} not configured"}
            if self._active:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    log.exception("profile stop during restart failed")
                self._active = False
            if first_n > 0:
                self.first_n = first_n
            self._captures += 1
            base = self.directory.rstrip("/").rsplit("/capture-", 1)[0]
            self.directory = os.path.join(
                base, f"capture-{self._captures:04d}"
            )
            self._seen = 0
            self._done = False
            log.info(
                "profile hook re-armed: next %d executions -> %s",
                self.first_n, self.directory,
            )
        return self.to_dict() | {"restarted": True}

    @contextmanager
    def capture(self):
        """Wrap one device execution; starts the trace on the first
        call, stops it after ``first_n``. Any profiler failure disables
        the hook rather than failing the query."""
        if self._done:
            yield
            return
        with self._lock:
            start = not self._active and self._seen == 0
            if start:
                try:
                    import jax

                    jax.profiler.start_trace(self.directory)
                    self._active = True
                    log.info(
                        "profiling first %d device executions -> %s",
                        self.first_n, self.directory,
                    )
                except Exception:
                    log.exception("PIO_TPU_PROFILE start failed; disabled")
                    self._done = True
        try:
            yield
        finally:
            with self._lock:
                if self._active:
                    self._seen += 1
                    if self._seen >= self.first_n:
                        try:
                            import jax

                            jax.profiler.stop_trace()
                            log.info(
                                "profile trace written to %s", self.directory
                            )
                        except Exception:
                            log.exception("PIO_TPU_PROFILE stop failed")
                        self._active = False
                        self._done = True
