"""Opt-in JAX profiler hook for serving: ``PIO_TPU_PROFILE=dir``.

Training already supports ``--profile-dir`` (a trace of the whole run);
serving needs something narrower — profiling every query forever would
drown the trace and tax the hot path. This hook captures ONE
``jax.profiler`` trace covering the first N device executions after
deploy (N from ``PIO_TPU_PROFILE_EXECUTIONS``, default 8: enough to see
both the bucket-compile execution and warm steady-state dispatches),
then gets out of the way permanently. View with tensorboard/xprof.
"""

from __future__ import annotations

import logging
import os
import threading
from contextlib import contextmanager

log = logging.getLogger("pio_tpu.obs")

ENV_DIR = "PIO_TPU_PROFILE"
ENV_N = "PIO_TPU_PROFILE_EXECUTIONS"


class DeviceProfileHook:
    """Context manager factory wrapped around the device-execute stage.

    Inert (zero overhead beyond one attribute check) unless constructed
    with a directory — the serving services build it from the
    environment via :func:`from_env`.
    """

    def __init__(self, directory: str = "", first_n: int = 8):
        self.directory = directory
        self.first_n = first_n
        self._lock = threading.Lock()
        self._seen = 0
        self._active = False
        self._done = not directory

    @classmethod
    def from_env(cls) -> "DeviceProfileHook":
        from pio_tpu.utils.envutil import env_int

        directory = os.environ.get(ENV_DIR, "")
        return cls(directory, env_int(ENV_N, 8, positive=True))

    @property
    def enabled(self) -> bool:
        return bool(self.directory) and not self._done

    @contextmanager
    def capture(self):
        """Wrap one device execution; starts the trace on the first
        call, stops it after ``first_n``. Any profiler failure disables
        the hook rather than failing the query."""
        if self._done:
            yield
            return
        with self._lock:
            start = not self._active and self._seen == 0
            if start:
                try:
                    import jax

                    jax.profiler.start_trace(self.directory)
                    self._active = True
                    log.info(
                        "profiling first %d device executions -> %s",
                        self.first_n, self.directory,
                    )
                except Exception:
                    log.exception("PIO_TPU_PROFILE start failed; disabled")
                    self._done = True
        try:
            yield
        finally:
            with self._lock:
                if self._active:
                    self._seen += 1
                    if self._seen >= self.first_n:
                        try:
                            import jax

                            jax.profiler.stop_trace()
                            log.info(
                                "profile trace written to %s", self.directory
                            )
                        except Exception:
                            log.exception("PIO_TPU_PROFILE stop failed")
                        self._active = False
                        self._done = True
