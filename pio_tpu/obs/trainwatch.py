"""Training telemetry plane — step-stream metrics, live progress, run ledger.

Serving got deep traces, SLOs and fleet federation; training exposed only
the four coarse ``pio_tpu_train_stage_seconds`` phases and two stream
counters. This module is the training-side plane (ISSUE 16):

- **StepRecorder**: the per-run telemetry hub. Training loops report
  step batches into it (loss window, examples, per-step seconds, h2d
  bytes, stream overlap); it feeds the step-stream metric families
  (``pio_tpu_train_steps_total``, ``pio_tpu_train_loss``,
  ``pio_tpu_train_step_seconds``, ``pio_tpu_train_examples_total``) and
  renders the ``/train.json`` progress payload the trainer status
  sidecar serves (phase, step/epoch/ETA, loss window, feed stats,
  per-device resident bytes).
- **Active-recorder hooks**: training loops call the module-level
  :func:`record_steps` / :func:`record_h2d` / :func:`set_phase` etc.,
  which are cheap no-ops unless a run activated a recorder — algorithm
  code never threads a recorder through its signatures, and library
  callers (tests, bench) pay nothing.
- **Run registry**: every ``run_train`` appends a flat JSON record to
  ``$PIO_TPU_HOME/runs/<engine-id>.jsonl``; ``pio runs`` lists the
  ledger and diffs consecutive runs with the same direction-aware
  regression logic bench's history ledger uses (:func:`delta_rows` is
  the shared core — bench delegates here).

Failpoints: ``trainwatch.record`` / ``trainwatch.payload`` /
``trainwatch.append`` (fault-injection surface for the telemetry plane —
a broken recorder must never break training itself, and the run-ledger
append is torn-write-testable).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pio_tpu.utils import knobs
from pio_tpu.obs.metrics import REGISTRY, monotonic_s

#: steps retired by training loops (streamed or staged), per algorithm
_STEPS = REGISTRY.counter(
    "pio_tpu_train_steps_total",
    "Optimizer steps retired by training loops",
    ("algo",),
)

#: most recent training loss (ALS has no per-step loss; absent there)
_LOSS = REGISTRY.gauge(
    "pio_tpu_train_loss",
    "Most recent training loss reported by the step stream",
    ("algo",),
)

#: per-step wall seconds — steps inside one compiled scan chunk share
#: the chunk's mean (per-step timing is unmeasurable inside lax.scan),
#: so each observation covers one recorded step batch
_STEP_SECONDS = REGISTRY.histogram(
    "pio_tpu_train_step_seconds",
    "Mean per-step wall seconds over each recorded step batch",
    ("algo",),
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)

#: training examples consumed (batch rows for SGD loops, rating edges
#: for the ALS normal-equation accumulators)
_EXAMPLES = REGISTRY.counter(
    "pio_tpu_train_examples_total",
    "Training examples consumed by training loops",
    ("algo",),
)


class StepRecorder:
    """Per-run telemetry hub behind ``/train.json``.

    Thread-safe by design: the training loop writes from the driver
    thread while the status sidecar's HTTP thread reads payloads, so
    every mutation and snapshot takes the internal lock. One recorder
    covers one run (possibly several algorithms in sequence — each
    :meth:`begin_algo` resets the per-algo window but keeps run totals).
    """

    def __init__(self, run_id: str, engine_id: str = "", *,
                 loss_window: int = 64):
        self._lock = threading.Lock()
        self.run_id = run_id
        self.engine_id = engine_id
        self.started_s = monotonic_s()
        self.phase = "start"
        self.algo = ""
        self.algo_index = -1
        self.algo_started_s: Optional[float] = None
        self.total_steps = 0
        self.steps_done = 0
        self.examples_done = 0
        self.n_batches = 0
        self.streamed = False
        self.n_stream = 0
        self.params_per_device_bytes = 0
        self.h2d_bytes = 0
        self.overlap_ratio: Optional[float] = None
        self.step_seconds = 0.0
        self.last_loss: Optional[float] = None
        self.losses: collections.deque = collections.deque(
            maxlen=max(1, loss_window)
        )
        self.phases: Dict[str, float] = {}

    # -- writes (training loop side) ------------------------------------

    def set_phase(self, name: str) -> None:
        with self._lock:
            self.phase = name

    def set_phase_seconds(self, name: str, dur_s: float) -> None:
        with self._lock:
            self.phases[name] = round(float(dur_s), 3)

    def begin_algo(self, algo: str, *, total_steps: int,
                   n_batches: int = 0, streamed: bool = False,
                   n_stream: int = 0, per_device_bytes: int = 0) -> None:
        """Open one algorithm's training window (resets per-algo
        progress; run-level totals like h2d bytes accumulate across)."""
        with self._lock:
            self.algo = algo
            self.algo_index += 1
            self.algo_started_s = monotonic_s()
            self.total_steps = int(total_steps)
            self.steps_done = 0
            self.examples_done = 0
            self.step_seconds = 0.0
            self.n_batches = int(n_batches)
            self.streamed = bool(streamed)
            self.n_stream = int(n_stream)
            self.params_per_device_bytes = int(per_device_bytes)
            self.last_loss = None
            self.losses.clear()

    def record_steps(self, n: int, *,
                     losses: Optional[Sequence[float]] = None,
                     examples: int = 0,
                     dur_s: Optional[float] = None) -> None:
        """Report ``n`` retired steps (one drained scan chunk, one
        streamed span, or one ALS chunk with ``n=0`` + edge examples)."""
        from pio_tpu.faults import failpoint

        failpoint("trainwatch.record")
        with self._lock:
            algo = self.algo or "unknown"
            self.steps_done += int(n)
            self.examples_done += int(examples)
            if n:
                _STEPS.inc(int(n), algo=algo)
            if examples:
                _EXAMPLES.inc(int(examples), algo=algo)
            if losses is not None and len(losses) > 0:
                for v in losses:
                    self.losses.append(float(v))
                self.last_loss = float(losses[-1])
                _LOSS.set(self.last_loss, algo=algo)
            if dur_s is not None and n > 0:
                self.step_seconds += float(dur_s)
                _STEP_SECONDS.observe(float(dur_s) / int(n), algo=algo)

    def record_h2d(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_bytes += int(nbytes)

    def set_stream(self, streamed: bool, n_stream: int = 0) -> None:
        """Late stream-mode stamp (ALS decides streaming after its
        algo window opened)."""
        with self._lock:
            self.streamed = bool(streamed)
            self.n_stream = int(n_stream)

    def set_overlap(self, ratio: float) -> None:
        with self._lock:
            self.overlap_ratio = float(ratio)

    # -- reads (sidecar / registry side) --------------------------------

    # pio: endpoint=/train.json
    def payload(self) -> dict:
        """The ``/train.json`` body (see docs/observability.md)."""
        from pio_tpu.faults import failpoint

        failpoint("trainwatch.payload")
        with self._lock:
            now = monotonic_s()
            elapsed = now - self.started_s
            algo_elapsed = (
                now - self.algo_started_s
                if self.algo_started_s is not None else None
            )
            progress = (
                self.steps_done / self.total_steps
                if self.total_steps > 0 else None
            )
            eta = None
            if (algo_elapsed and self.steps_done > 0
                    and self.total_steps > self.steps_done):
                rate = self.steps_done / algo_elapsed
                if rate > 0:
                    eta = round(
                        (self.total_steps - self.steps_done) / rate, 1
                    )
            eps = None
            if algo_elapsed and algo_elapsed > 0 and self.examples_done:
                eps = round(self.examples_done / algo_elapsed, 1)
            epoch = (
                round(self.steps_done / self.n_batches, 3)
                if self.n_batches > 0 else None
            )
            return {
                "runId": self.run_id,
                "engineId": self.engine_id,
                "phase": self.phase,
                "algo": self.algo or None,
                "algoIndex": self.algo_index if self.algo_index >= 0
                else None,
                "elapsedSeconds": round(elapsed, 3),
                "step": self.steps_done,
                "totalSteps": self.total_steps,
                "epoch": epoch,
                "progress": round(progress, 4)
                if progress is not None else None,
                "etaSeconds": eta,
                "examples": self.examples_done,
                "examplesPerSecond": eps,
                "loss": self.last_loss,
                "lossWindow": [round(v, 6) for v in self.losses],
                "stream": {
                    "streamed": self.streamed,
                    "chunks": self.n_stream,
                    "h2dBytes": self.h2d_bytes,
                    "overlapRatio": self.overlap_ratio,
                },
                "paramsPerDeviceBytes": self.params_per_device_bytes,
                "phases": dict(self.phases),
            }

    def summary(self) -> dict:
        """Flat step summary for the run-ledger record."""
        with self._lock:
            now = monotonic_s()
            algo_elapsed = (
                now - self.algo_started_s
                if self.algo_started_s is not None else None
            )
            eps = None
            if algo_elapsed and algo_elapsed > 0 and self.examples_done:
                eps = round(self.examples_done / algo_elapsed, 1)
            window_mean = (
                round(sum(self.losses) / len(self.losses), 6)
                if self.losses else None
            )
            return {
                "algo": self.algo or None,
                "steps": self.steps_done,
                "examples": self.examples_done,
                "examples_per_sec": eps,
                "final_loss": round(self.last_loss, 6)
                if self.last_loss is not None else None,
                "loss_window_mean": window_mean,
                "h2d_bytes": self.h2d_bytes,
                "overlap_ratio": self.overlap_ratio,
                "streamed": self.streamed,
                "stream_chunks": self.n_stream,
            }


# ---------------------------------------------------------------------------
# active recorder — module-global (NOT a contextvar: the sidecar HTTP
# thread must see the driver thread's recorder)
# ---------------------------------------------------------------------------

_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[StepRecorder] = None


def activate(rec: StepRecorder) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = rec


def deactivate() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def active_recorder() -> Optional[StepRecorder]:
    return _ACTIVE


@contextlib.contextmanager
def recording(rec: StepRecorder):
    """Install ``rec`` as the process's active recorder for the block."""
    activate(rec)
    try:
        yield rec
    finally:
        deactivate()


def set_phase(name: str) -> None:
    rec = _ACTIVE
    if rec is not None:
        rec.set_phase(name)


def begin_algo(algo: str, **kw) -> None:
    rec = _ACTIVE
    if rec is not None:
        rec.begin_algo(algo, **kw)


def record_steps(n: int, **kw) -> None:
    rec = _ACTIVE
    if rec is not None:
        rec.record_steps(n, **kw)


def record_h2d(nbytes: int) -> None:
    rec = _ACTIVE
    if rec is not None:
        rec.record_h2d(nbytes)


def set_overlap(ratio: float) -> None:
    rec = _ACTIVE
    if rec is not None:
        rec.set_overlap(ratio)


def set_stream(streamed: bool, n_stream: int = 0) -> None:
    rec = _ACTIVE
    if rec is not None:
        rec.set_stream(streamed, n_stream)


# ---------------------------------------------------------------------------
# direction-aware deltas — the regression core shared with bench's
# history ledger (bench.py history_delta_table delegates here)
# ---------------------------------------------------------------------------


def delta_rows(prev: dict, cur: dict,
               fields: Sequence[Tuple[str, str]],
               threshold: float) -> Tuple[list, list]:
    """``(rows, regressed_fields)`` comparing two flat records.

    ``fields`` are ``(name, direction)`` pairs, direction ``"up"`` or
    ``"down"`` (the *good* direction). Each row is
    ``(field, prev, cur, delta_str, tag)``; a field moves onto the
    regressed list when it moves AGAINST its direction by more than
    ``threshold`` (fractional). Non-numeric or missing values skip.
    """
    rows: list = []
    regressed: list = []
    for field, direction in fields:
        a, b = prev.get(field), cur.get(field)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            continue
        pct = (b - a) / a if a else None
        if pct is None:
            tag = ""
            delta = "n/a"
        else:
            delta = f"{pct * 100:+.1f}%"
            bad = pct < -threshold if direction == "up" else pct > threshold
            good = pct > threshold if direction == "up" else pct < -threshold
            tag = "  REGRESSION" if bad else ("  improved" if good else "")
            if bad:
                regressed.append(field)
        rows.append((field, a, b, delta, tag))
    return rows, regressed


# ---------------------------------------------------------------------------
# run registry — $PIO_TPU_HOME/runs/<engine-id>.jsonl, one flat record
# per run_train (COMPLETED and FAILED both: a crashed run is trend data)
# ---------------------------------------------------------------------------

DEFAULT_RUN_THRESHOLD = 0.05

#: run-ledger trajectory fields and their good direction; ``phase_*``
#: durations join dynamically (direction "down") when diffing
RUN_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("train_seconds", "down"),
    ("examples_per_sec", "up"),
    ("final_loss", "down"),
    ("loss_window_mean", "down"),
    ("overlap_ratio", "up"),
)


def runs_path(engine_id: str) -> str:
    home = knobs.knob_str("PIO_TPU_HOME") or os.path.expanduser("~/.pio_tpu")
    return os.path.join(home, "runs", f"{engine_id}.jsonl")


def run_record(*, run_id: str, engine_id: str, status: str,
               train_seconds: float, phases: Dict[str, float],
               params_hash: str, step_summary: Optional[dict] = None,
               num_devices: Optional[int] = None,
               shard_manifest: Optional[str] = None,
               timestamp: Optional[str] = None,
               error: Optional[str] = None) -> dict:
    """One runs.jsonl row. Flat where it matters: the step summary's
    numeric fields are lifted to the top level so :func:`delta_rows`
    can diff two rows directly."""
    if timestamp is None:
        import datetime as _dt

        timestamp = _dt.datetime.now(_dt.timezone.utc).isoformat(
            timespec="seconds"
        )
    rec: Dict[str, Any] = {
        "run_id": run_id,
        "engine_id": engine_id,
        "timestamp": timestamp,
        "status": status,
        "params_hash": params_hash,
        "train_seconds": round(float(train_seconds), 3),
        "num_devices": num_devices,
        "shard_manifest": shard_manifest,
    }
    for name, dur in (phases or {}).items():
        rec[f"phase_{name}"] = round(float(dur), 3)
    if step_summary:
        rec["step_summary"] = dict(step_summary)
        for key in ("examples_per_sec", "final_loss", "loss_window_mean",
                    "overlap_ratio", "steps", "examples"):
            if step_summary.get(key) is not None:
                rec[key] = step_summary[key]
    if error:
        rec["error"] = error[-500:]
    return rec


def append_run(record: dict, path: Optional[str] = None) -> str:
    """Append one record to the engine's ledger; returns the path."""
    from pio_tpu.faults import failpoint

    failpoint("trainwatch.append")
    if path is None:
        path = runs_path(record.get("engine_id") or "unknown")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")
    return path


def read_runs(engine_id: Optional[str] = None,
              path: Optional[str] = None) -> List[dict]:
    """All parseable ledger rows (malformed lines — torn appends — are
    skipped, never fatal)."""
    if path is None:
        if engine_id is None:
            raise ValueError("read_runs needs engine_id or path")
        path = runs_path(engine_id)
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(row, dict):
                    out.append(row)
    except OSError:
        pass
    return out


def run_delta_table(prev: dict, cur: dict,
                    threshold: float = DEFAULT_RUN_THRESHOLD) -> Tuple[list, list]:
    """``(table_lines, regressed_fields)`` for two run-ledger rows —
    the static :data:`RUN_FIELDS` plus every ``phase_*`` duration both
    rows carry (direction "down": a slower phase is a regression)."""
    fields = list(RUN_FIELDS)
    phase_keys = sorted(
        k for k in cur
        if k.startswith("phase_") and k in prev
    )
    fields.extend((k, "down") for k in phase_keys)
    rows, regressed = delta_rows(prev, cur, fields, threshold)
    lines = [
        f"run delta vs {prev.get('run_id') or '?'} "
        f"({prev.get('timestamp') or '?'}), threshold "
        f"{threshold * 100:.1f}%:",
        f"  {'field':<24} {'prev':>12} {'now':>12} {'delta':>9}",
    ]
    for field, a, b, delta, tag in rows:
        lines.append(f"  {field:<24} {a:>12} {b:>12} {delta:>9}{tag}")
    if not rows:
        lines.append("  (no comparable numeric fields)")
    return lines, regressed
