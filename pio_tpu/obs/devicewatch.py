"""Device telemetry plane — live HBM accounting + compile attribution
(ISSUE 17).

The server observed engines (queries, stages, replication, training
progress) but ran blind to its accelerators: every device-memory fact
in the tree was an estimate (``per_device_nbytes`` bookkeeping) and
every compile an inference from retrace counters. This module is the
third telemetry plane, mirroring the fleet (ISSUE 11) and training
(ISSUE 16) planes, with three surfaces:

- **Sampler** — :class:`DeviceWatch` periodically reads per-device
  ``Device.memory_stats()`` (bytes_in_use / peak / limit) where the
  backend supports it and falls back to a book-kept ledger (resident
  scorers, shard placements, donated buffers, stream carry) on
  backends that don't (CPU). Sampling runs on its OWN thread — no
  device sync is ever injected into a dispatch path.
- **Compile attribution** — the in-tree jit entry points (bucket
  warmup, resident scorer programs, stream dispatch, trainer steps)
  wrap their cache-fresh dispatches in :func:`compile_span`, so every
  trace+compile lands in ``pio_tpu_xla_compile_total{site}`` and a
  ``pio_tpu_xla_compile_seconds{site}`` histogram with trace
  exemplars. Steady-state serving must show the counters FLAT — the
  ISSUE-7 "zero retraces" claim becomes a directly monitored
  invariant. ("Compile" here means a dispatch whose site-level program
  cache had no entry for the shape key: the span brackets jit's
  trace+compile entry. A shape the global jit cache already holds —
  e.g. a hot-swap re-warm over an unchanged bucket ladder — is NOT
  recounted, matching what XLA actually does.)
- **Endpoints** — ``payload()`` renders ``GET /device.json`` on the
  query server and the trainer status sidecar; the fleet aggregator
  federates it into ``/fleet.json`` as a per-member ``devices`` block
  (the budget-driven-eviction input of ROADMAP item 6); ``pio top``
  polls it into a live terminal table and ``pio dashboard`` renders
  ``/devices.html``.

Like trainwatch, the active watch is a module GLOBAL under a lock (not
a contextvar): the status sidecar's HTTP thread must see the watch the
driver thread activated. Library code records through the module-level
no-op hooks (``ledger_place``/``record_compile``/…) which cost one
``None`` check when no watch is active.

Headroom is accounted against ``PIO_TPU_DEVICE_BUDGET_BYTES`` (the
same env :mod:`pio_tpu.parallel.partition` enforces at placement):
``pio_tpu_device_budget_headroom_bytes = budget - max(bytes_in_use)``.
When live ``memory_stats()`` and the ledger disagree the gap is
exported as ``pio_tpu_device_estimate_drift_bytes{device}`` — the
estimate-honesty gauge ROADMAP item 3 asked for.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.obs.metrics import (
    REGISTRY,
    MetricsRegistry,
    monotonic_s,
)

log = logging.getLogger("pio_tpu.obs.devicewatch")

#: sampler interval; the thread wakes, samples, sleeps — never touches
#: a dispatch path
INTERVAL_ENV = "PIO_TPU_DEVICEWATCH_INTERVAL_S"
DEFAULT_INTERVAL_S = 2.0

#: shared with pio_tpu.parallel.partition (placement enforcement reads
#: the same budget this plane reports headroom against)
BUDGET_ENV = "PIO_TPU_DEVICE_BUDGET_BYTES"

#: set to ``0`` to keep the sampler thread off (payload() then samples
#: on demand — the endpoint still answers, just without a fresh series)
SAMPLER_ENV = "PIO_TPU_DEVICEWATCH"

#: documented compile-attribution sites (the jit entry points wrapped
#: in-tree); cells are pre-created per site so pool-mode shm mirroring
#: sees them before the bind
COMPILE_SITES = (
    "bucket_warmup",     # deploy-time bucket ladder sweep (query server)
    "bucket_dispatch",   # a LIVE dispatch that retraced (should be 0)
    "resident_scorer",   # device-resident scorer program per bucket
    "stream_dispatch",   # streamed-feed chunk program (training h2d path)
    "train_step",        # staged/full trainer chunk programs
)

#: ledger categories the fallback accounting books under
LEDGER_CATEGORIES = ("resident", "donated", "shard", "stream")

#: compile latencies span warmup-sweep milliseconds to multi-second
#: first traces; the default request-latency buckets top out too low
COMPILE_BUCKETS = (
    0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _register_families(reg: MetricsRegistry) -> dict:
    """Create (or fetch — registration is idempotent) the device
    families on ``reg``. Gauges never bind to the pool segment, so the
    per-device series are safe on a pool worker's registry."""
    return {
        "in_use": reg.gauge(
            "pio_tpu_device_bytes_in_use",
            "Bytes currently allocated on the device (memory_stats "
            "where supported, else the book-kept ledger)",
            ("device",),
        ),
        "peak": reg.gauge(
            "pio_tpu_device_peak_bytes",
            "High-water allocation mark per device",
            ("device",),
        ),
        "limit": reg.gauge(
            "pio_tpu_device_limit_bytes",
            "Allocatable byte limit the backend reports per device",
            ("device",),
        ),
        "headroom": reg.gauge(
            "pio_tpu_device_budget_headroom_bytes",
            "PIO_TPU_DEVICE_BUDGET_BYTES minus the busiest device's "
            "bytes_in_use (only set when a budget is configured)",
        ),
        "drift": reg.gauge(
            "pio_tpu_device_estimate_drift_bytes",
            "memory_stats bytes_in_use minus the book-kept ledger for "
            "the device (set when both sides have data and disagree)",
            ("device",),
        ),
        "compile_total": reg.counter(
            "pio_tpu_xla_compile_total",
            "Trace+compile entries attributed per in-tree jit site; "
            "steady-state serving must hold these flat",
            ("site",),
        ),
        "compile_seconds": reg.histogram(
            "pio_tpu_xla_compile_seconds",
            "Wall seconds of attributed trace+compile dispatches, with "
            "trace exemplars",
            ("site",),
            buckets=COMPILE_BUCKETS,
        ),
    }


# the process-global families exist from import on (trainer sidecar and
# stream/partition hooks render through REGISTRY)
_register_families(REGISTRY)


def _active_trace_id() -> Optional[str]:
    try:
        from pio_tpu.obs.tracing import active_trace

        h = active_trace()
        return h.trace_id if h is not None else None
    except Exception:
        return None


def shape_key(tree: Any) -> tuple:
    """Hashable per-leaf shape tuple for ``fresh``-keying a pytree
    dispatch (a chunk with new leaf shapes is a new program)."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = [tree]
    return tuple(tuple(getattr(leaf, "shape", ())) for leaf in leaves)


class DeviceWatch:
    """Per-process (or per-daemon) device telemetry hub.

    The query server holds one on its per-instance registry; a training
    run activates one on the process-global registry for the sidecar.
    All mutation is lock-guarded host bookkeeping — the only device
    interaction is ``memory_stats()`` reads from the sampler thread.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        interval_s: Optional[float] = None,
        budget_bytes: Optional[int] = None,
        stats_fn: Optional[Callable[[], List[tuple]]] = None,
    ):
        reg = registry if registry is not None else REGISTRY
        self.registry = reg
        fams = _register_families(reg)
        self._g_in_use = fams["in_use"]
        self._g_peak = fams["peak"]
        self._g_limit = fams["limit"]
        self._g_headroom = fams["headroom"]
        self._g_drift = fams["drift"]
        self._compile_total = fams["compile_total"]
        self._compile_seconds = fams["compile_seconds"]
        # pre-created site cells: pool shm slots must exist before any
        # enable_pool bind, and hot-path increments skip labels()
        self._compile_cells = {
            s: self._compile_total.labels(s) for s in COMPILE_SITES
        }
        for s in COMPILE_SITES:
            self._compile_seconds.labels(s)
        if interval_s is None:
            interval_s = knobs.knob_float(INTERVAL_ENV)
        self.interval_s = max(0.05, float(interval_s))
        if budget_bytes is None:
            budget_bytes = knobs.knob_int(BUDGET_ENV)
        self.budget_bytes = int(budget_bytes)
        self._stats_fn = stats_fn
        self._lock = threading.Lock()
        #: (category, key) → placement row; the CPU-fallback accounting
        self._ledger: Dict[Tuple[str, str], dict] = {}
        #: (site, key) freshness set backing :meth:`fresh`
        self._seen: set = set()
        #: site → compile table row (count, seconds, last trace)
        self._compiles: Dict[str, dict] = {}
        self._generation: Optional[int] = None
        self._peaks: Dict[str, int] = {}
        self._rows: List[dict] = []
        self._mode = "ledger"
        self._samples = 0
        self._started_at = monotonic_s()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- compile attribution -----------------------------------------------
    def fresh(self, site: str, key: Any) -> bool:
        """First sighting of ``(site, key)``? ``key=None`` is always
        fresh (unconditional sites like the warmup sweep own their own
        dedup via bucket keys)."""
        if key is None:
            return True
        k = (site, key)
        with self._lock:
            if k in self._seen:
                return False
            self._seen.add(k)
            return True

    def record_compile(
        self,
        site: str,
        seconds: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        cell = self._compile_cells.get(site)
        if cell is not None:
            cell.inc()
        else:
            self._compile_total.inc(site=site)
        with self._lock:
            row = self._compiles.setdefault(
                site,
                {"count": 0, "seconds": 0.0, "lastS": None,
                 "lastTraceId": None},
            )
            row["count"] += 1
            if seconds is not None:
                row["seconds"] = round(row["seconds"] + float(seconds), 6)
                row["lastS"] = round(float(seconds), 6)
            if trace_id:
                row["lastTraceId"] = trace_id
        if seconds is not None:
            self._compile_seconds.observe(
                float(seconds), exemplar=trace_id, site=site
            )

    @contextlib.contextmanager
    def span(self, site: str, key: Any = None):
        """Bracket a possibly-compiling dispatch: yields True (and
        records count + wall seconds + trace exemplar) when ``key`` is
        fresh for ``site``, False (no record, no timing) otherwise."""
        if not self.fresh(site, key):
            yield False
            return
        t0 = monotonic_s()
        yield True
        self.record_compile(
            site, monotonic_s() - t0, trace_id=_active_trace_id()
        )

    def compile_counts(self) -> Dict[str, int]:
        with self._lock:
            return {s: r["count"] for s, r in self._compiles.items()}

    # -- ledger -------------------------------------------------------------
    def ledger_place(
        self,
        category: str,
        key: Any,
        nbytes: int,
        device: int = 0,
        name: Optional[str] = None,
    ) -> None:
        """Book ``nbytes`` resident under ``(category, key)``; replaces
        a prior placement under the same key (re-place = resize)."""
        with self._lock:
            self._ledger[(category, str(key))] = {
                "category": category,
                "key": str(key),
                "name": name or str(key),
                "bytes": int(nbytes),
                "device": int(device),
                "generation": self._generation,
            }

    def ledger_release(self, category: str, key: Any) -> None:
        with self._lock:
            self._ledger.pop((category, str(key)), None)

    def ledger_clear(self, category: Optional[str] = None) -> None:
        with self._lock:
            if category is None:
                self._ledger.clear()
                return
            for k in [k for k in self._ledger if k[0] == category]:
                del self._ledger[k]

    def stream_carry(self, delta: int) -> None:
        """Streamed-feed in-flight bytes: chunks add on put, release on
        (non-retained) dispatch or feed finalize; floored at zero."""
        with self._lock:
            row = self._ledger.get(("stream", "carry"))
            if row is None:
                row = {
                    "category": "stream", "key": "carry",
                    "name": "stream carry", "bytes": 0, "device": 0,
                    "generation": self._generation,
                }
                self._ledger[("stream", "carry")] = row
            row["bytes"] = max(0, row["bytes"] + int(delta))

    def ledger_bytes(self, device: Optional[int] = None) -> int:
        with self._lock:
            return sum(
                row["bytes"] for row in self._ledger.values()
                if device is None or row["device"] == int(device)
            )

    def set_generation(self, gen: int) -> None:
        """Stamp the serving generation (hot-swap bump). Placements
        booked before the swap installed (generation still unknown)
        are restamped with the generation they went live under."""
        with self._lock:
            self._generation = int(gen)
            for row in self._ledger.values():
                if row["generation"] is None:
                    row["generation"] = int(gen)

    # -- sampling -----------------------------------------------------------
    def _device_stats(self) -> List[tuple]:
        """``[(label, memory_stats_or_None, device_index)]`` for every
        visible device; synthetic rows from the ledger when no backend
        is importable at all."""
        if self._stats_fn is not None:
            return self._stats_fn()
        try:
            import jax

            devices = jax.devices()
        except Exception:
            devices = None
        if not devices:
            with self._lock:
                idxs = sorted(
                    {row["device"] for row in self._ledger.values()}
                ) or [0]
            return [(f"device:{i}", None, i) for i in idxs]
        out = []
        for i, d in enumerate(devices):
            stats = None
            try:
                ms = d.memory_stats()
                if ms and ms.get("bytes_in_use") is not None:
                    stats = ms
            except Exception:
                stats = None
            label = f"{getattr(d, 'platform', 'device')}:" \
                    f"{getattr(d, 'id', i)}"
            out.append((label, stats, i))
        return out

    def sample(self) -> List[dict]:
        """One telemetry pass: read (or book-keep) every device's bytes,
        update the gauges, compute headroom and estimate drift. Host
        work + guarded ``memory_stats`` reads only — never a sync."""
        from pio_tpu.faults import failpoint

        failpoint("devicewatch.sample")
        entries = self._device_stats()
        live = any(stats is not None for _, stats, _ in entries)
        rows: List[dict] = []
        max_in_use = 0
        for label, stats, idx in entries:
            ledger = self.ledger_bytes(device=idx)
            if stats is not None:
                in_use = int(stats.get("bytes_in_use") or 0)
                peak = int(stats.get("peak_bytes_in_use") or in_use)
                limit = stats.get("bytes_limit")
                limit = int(limit) if limit else None
                source = "memory_stats"
            else:
                in_use, peak, limit = ledger, ledger, None
                source = "ledger"
            with self._lock:
                peak = max(self._peaks.get(label, 0), peak, in_use)
                self._peaks[label] = peak
            drift = (
                in_use - ledger
                if (stats is not None and ledger > 0) else None
            )
            rows.append({
                "device": label,
                "bytesInUse": in_use,
                "peakBytes": peak,
                "limitBytes": limit,
                "ledgerBytes": ledger,
                "driftBytes": drift,
                "source": source,
            })
            max_in_use = max(max_in_use, in_use)
            self._g_in_use.set(float(in_use), device=label)
            self._g_peak.set(float(peak), device=label)
            if limit is not None:
                self._g_limit.set(float(limit), device=label)
            if drift is not None:
                self._g_drift.set(float(drift), device=label)
        if self.budget_bytes > 0:
            self._g_headroom.set(float(self.budget_bytes - max_in_use))
        with self._lock:
            self._rows = rows
            self._mode = "live" if live else "ledger"
            self._samples += 1
        return rows

    def measured_bytes(self) -> Optional[int]:
        """Backend-measured total bytes_in_use from the last sample, or
        None when only the ledger is available (CPU) — the honesty
        companion to the estimated ``paramBytes`` in ``/stats.json``."""
        with self._lock:
            if self._mode != "live":
                return None
            return sum(
                r["bytesInUse"] for r in self._rows
                if r["source"] == "memory_stats"
            )

    # -- payload ------------------------------------------------------------
    # pio: endpoint=/device.json
    def payload(self) -> dict:
        """The ``GET /device.json`` body (schema in
        docs/observability.md). Always samples inline — sample() is
        host-only work and /device.json is a telemetry endpoint, not
        the dispatch hot path; serving the background thread's last
        pass instead would leave scrapes up to interval_s stale (a
        scrape right after placement would show an empty device)."""
        from pio_tpu.faults import failpoint

        failpoint("devicewatch.payload")
        self.sample()
        with self._lock:
            rows = [dict(r) for r in self._rows]
            by_category: Dict[str, int] = {}
            placements = []
            for row in self._ledger.values():
                by_category[row["category"]] = (
                    by_category.get(row["category"], 0) + row["bytes"]
                )
                placements.append(dict(row))
            compiles = {
                s: dict(r) for s, r in sorted(self._compiles.items())
            }
            generation = self._generation
            samples = self._samples
            mode = self._mode
        placements.sort(
            key=lambda p: (
                p["generation"] if p["generation"] is not None else -1,
                p["category"], p["name"],
            )
        )
        max_in_use = max((r["bytesInUse"] for r in rows), default=0)
        return {
            "mode": mode,
            # pio: disable=wallclock-duration (asOf is a true timestamp)
            "asOf": time.time(),
            "uptimeS": round(monotonic_s() - self._started_at, 3),
            "intervalS": self.interval_s,
            "samples": samples,
            "sampler": self._thread is not None,
            "budgetBytes": self.budget_bytes or None,
            "headroomBytes": (
                self.budget_bytes - max_in_use
                if self.budget_bytes > 0 else None
            ),
            "generation": generation,
            "devices": rows,
            "ledger": {
                "totalBytes": sum(by_category.values()),
                "byCategory": by_category,
            },
            "placements": placements,
            "compiles": {
                "total": sum(r["count"] for r in compiles.values()),
                "sites": compiles,
            },
        }

    # -- sampler thread -----------------------------------------------------
    def start(self) -> "DeviceWatch":
        """Spawn the background sampler (idempotent). Daemon thread:
        the plane must never hold a process open."""
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        t = threading.Thread(
            target=self._run, name="pio-devicewatch", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def _run(self) -> None:
        while True:
            try:
                self.sample()
            except Exception:
                log.exception("device sample failed")
            if self._stop_ev.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


# ---------------------------------------------------------------------------
# module-global active watch (the trainwatch discipline: a LOCKED global,
# not a contextvar — the sidecar HTTP thread must see the driver's watch)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[DeviceWatch] = None
_ACTIVE_LOCK = threading.Lock()
#: last deactivated watch — bench reads a finished training run's peaks
_LAST: Optional[DeviceWatch] = None


def activate(watch: DeviceWatch) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = watch


def deactivate(watch: Optional[DeviceWatch] = None) -> None:
    """Clear the active watch; with ``watch`` given, only when it is
    still the active one (a later activation wins)."""
    global _ACTIVE, _LAST
    with _ACTIVE_LOCK:
        if watch is None or _ACTIVE is watch:
            if _ACTIVE is not None:
                _LAST = _ACTIVE
            _ACTIVE = None


def active_watch() -> Optional[DeviceWatch]:
    return _ACTIVE


def last_watch() -> Optional[DeviceWatch]:
    """The most recently deactivated watch (bench post-mortems)."""
    return _ACTIVE or _LAST


@contextlib.contextmanager
def watching(watch: DeviceWatch, sample: bool = True):
    """Activate ``watch`` (and run its sampler) for a scope — the
    training driver wraps the run so the status sidecar can serve
    ``/device.json`` while steps stream."""
    activate(watch)
    if sample:
        watch.start()
    try:
        yield watch
    finally:
        if sample:
            watch.stop()
        deactivate(watch)


# ---------------------------------------------------------------------------
# no-op hooks: library code calls these unconditionally; one None check
# when no watch is active
# ---------------------------------------------------------------------------

def record_compile(
    site: str,
    seconds: Optional[float] = None,
    trace_id: Optional[str] = None,
) -> None:
    w = _ACTIVE
    if w is not None:
        w.record_compile(site, seconds, trace_id=trace_id)


@contextlib.contextmanager
def compile_span(site: str, key: Any = None):
    """Module-level :meth:`DeviceWatch.span` against the active watch
    (yields False untimed when none is active or the key is stale)."""
    w = _ACTIVE
    if w is None:
        yield False
        return
    with w.span(site, key=key) as fresh:
        yield fresh


def ledger_place(
    category: str,
    key: Any,
    nbytes: int,
    device: int = 0,
    name: Optional[str] = None,
) -> None:
    w = _ACTIVE
    if w is not None:
        w.ledger_place(category, key, nbytes, device=device, name=name)


def ledger_release(category: str, key: Any) -> None:
    w = _ACTIVE
    if w is not None:
        w.ledger_release(category, key)


def ledger_clear(category: Optional[str] = None) -> None:
    w = _ACTIVE
    if w is not None:
        w.ledger_clear(category)


def stream_carry(delta: int) -> None:
    w = _ACTIVE
    if w is not None:
        w.stream_carry(delta)


def set_generation(gen: int) -> None:
    w = _ACTIVE
    if w is not None:
        w.set_generation(gen)
