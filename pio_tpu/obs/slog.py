"""Structured JSON logging with trace correlation.

Every serving daemon used to emit free-form stdlib log lines that could
not be joined to anything: not to the request trace that produced them,
not to a worker in the pool, not to a scrape. This module closes that
gap with three pieces:

- :class:`JsonLogHandler` — a ``logging.Handler`` that renders each
  record as ONE line of JSON (``ts/level/logger/msg/trace_id/span/
  worker`` plus exception text), so the serving daemons' stderr is
  machine-parseable by any log shipper without a custom grok pattern.
- A **trace contextvar**: :class:`pio_tpu.obs.tracing.Tracer` publishes
  the active ``(trace_id, span)`` here on entry and restores it on exit,
  so ANY log emitted inside a span — handler code, storage, an
  algorithm's own logger — carries the id of the request that caused it.
  ``/logs.json?trace_id=...`` then answers "what did request X log"
  and joins against the same id in ``/traces.json``.
- A bounded in-process **ring** of recent entries surfaced as
  ``GET /logs.json?level=&trace_id=&n=`` on the query, event and
  dashboard servers — the last N log lines without shell access to the
  serving host.

Volume is metered by ``pio_tpu_log_messages_total{level,logger}`` in the
process-global registry (a log-rate spike is an incident signal in its
own right); HTTP services re-expose those lines on their own ``/metrics``
via a collector (:func:`exposition_lines`).

The ring + counter are always on once :func:`install` runs (cheap: one
dict per record). JSON **console** rendering is opt-in — the CLI entry
points pass ``stream`` (or set ``PIO_TPU_LOG_JSON=1``) so interactive
``pytest``/REPL sessions keep the human format.
"""

from __future__ import annotations

import contextvars
import datetime as _dt
import io
import json
import logging
import sys
import threading
from typing import Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.obs.metrics import REGISTRY

#: the active (trace_id, span) for THIS thread/task — set by Tracer.trace
#: and _TraceHandle.span, read by every JsonLogHandler.emit. A contextvar
#: (not a threading.local) so async frameworks layered on top inherit it
#: across await points for free.
TRACE_CONTEXT: contextvars.ContextVar[Tuple[Optional[str], Optional[str]]] = \
    contextvars.ContextVar("pio_tpu_trace", default=(None, None))

#: log records by severity and origin logger (process-global registry:
#: logging has no per-service owner; HTTP services re-expose via
#: exposition_lines collectors)
_LOG_MESSAGES = REGISTRY.counter(
    "pio_tpu_log_messages_total",
    "Log records emitted, by level and logger",
    ("level", "logger"),
)

#: default ring capacity (override with PIO_TPU_LOG_RING)
DEFAULT_RING = 512


def current_trace_id() -> Optional[str]:
    """The trace id of the enclosing span, if any."""
    return TRACE_CONTEXT.get()[0]


class LogRing:
    """Bounded ring of structured log entries (dicts), oldest evicted."""

    def __init__(self, cap: int = DEFAULT_RING):
        self._lock = threading.Lock()
        self._cap = max(int(cap), 1)
        self._ring: List[dict] = []
        self._pos = 0
        self.dropped = 0  # entries evicted since start

    @property
    def cap(self) -> int:
        return self._cap

    def append(self, entry: dict) -> None:
        with self._lock:
            if len(self._ring) < self._cap:
                self._ring.append(entry)
            else:
                self._ring[self._pos] = entry
                self._pos = (self._pos + 1) % self._cap
                self.dropped += 1

    def tail(self, n: int = 100, level: Optional[str] = None,
             trace_id: Optional[str] = None,
             logger: Optional[str] = None) -> List[dict]:
        """The newest ``n`` entries matching the filters, in
        chronological order. ``level`` is a minimum severity (``WARNING``
        matches WARNING and above); ``trace_id`` an exact match;
        ``logger`` a name prefix."""
        min_no = None
        if level:
            min_no = logging.getLevelName(level.upper())
            if not isinstance(min_no, int):
                raise ValueError(f"unknown level {level!r}")
        with self._lock:
            # chronological: the tail after the cursor is oldest
            entries = self._ring[self._pos:] + self._ring[:self._pos]
        out = []
        for e in entries:
            if min_no is not None and e.get("levelno", 0) < min_no:
                continue
            if trace_id is not None and e.get("trace_id") != trace_id:
                continue
            if logger is not None and not str(
                e.get("logger", "")
            ).startswith(logger):
                continue
            out.append(e)
        return out[-n:] if n >= 0 else out

    def snapshot(self) -> List[dict]:
        return self.tail(n=-1)


def _public(entry: dict) -> dict:
    """The wire shape of one entry (drops the internal levelno)."""
    return {k: v for k, v in entry.items() if k != "levelno"}


class JsonLogHandler(logging.Handler):
    """Renders records as one-line JSON; feeds the ring + counter.

    ``stream`` is optional — without one the handler only records (ring
    + metrics), leaving console formatting to whatever other handlers
    are installed. With one (the CLI daemons pass stderr) every line the
    process logs becomes machine-parseable.
    """

    def __init__(self, ring: Optional[LogRing] = None,
                 stream: Optional[io.TextIOBase] = None,
                 worker: Optional[int] = None,
                 level: int = logging.DEBUG):
        super().__init__(level=level)
        self.ring = ring if ring is not None else LogRing()
        self.stream = stream
        self.worker = worker

    def entry_for(self, record: logging.LogRecord) -> dict:
        try:
            msg = record.getMessage()
        except Exception:  # a bad %-format must not kill the logger
            msg = str(record.msg)
        trace_id, span = TRACE_CONTEXT.get()
        entry = {
            "ts": _dt.datetime.fromtimestamp(
                record.created, _dt.timezone.utc
            ).isoformat(timespec="milliseconds"),
            "level": record.levelname,
            "levelno": record.levelno,
            "logger": record.name,
            "msg": msg,
            "trace_id": trace_id,
            "span": span,
            "worker": self.worker,
        }
        if record.exc_info and record.exc_info[0] is not None:
            try:
                entry["exc"] = logging.Formatter().formatException(
                    record.exc_info
                )
            except Exception:
                pass
        return entry

    def format_line(self, record: logging.LogRecord) -> str:
        return json.dumps(_public(self.entry_for(record)), default=str)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = self.entry_for(record)
            _LOG_MESSAGES.inc(level=record.levelname, logger=record.name)
            self.ring.append(entry)
            if self.stream is not None:
                self.stream.write(
                    json.dumps(_public(entry), default=str) + "\n"
                )
                self.stream.flush()
        except Exception:
            self.handleError(record)


# -- process-wide installation ---------------------------------------------
_install_lock = threading.Lock()
_handler: Optional[JsonLogHandler] = None


def install(stream: Optional[io.TextIOBase] = None,
            worker: Optional[int] = None,
            logger_name: str = "pio_tpu") -> JsonLogHandler:
    """Attach ONE JsonLogHandler to the ``pio_tpu`` logger tree
    (idempotent — later calls may upgrade a record-only handler with a
    stream or a worker index, never stack a second handler).

    ``PIO_TPU_LOG_JSON=1`` forces console JSON even when no stream is
    passed (containerized deploys where stdout IS the log shipper).
    """
    global _handler
    with _install_lock:
        if _handler is None:
            if stream is None and knobs.knob_str("PIO_TPU_LOG_JSON") == "1":
                stream = sys.stderr

            ring = LogRing(knobs.knob_int("PIO_TPU_LOG_RING"))
            _handler = JsonLogHandler(ring, stream=stream, worker=worker)
            target = logging.getLogger(logger_name)
            target.addHandler(_handler)
            if target.level == logging.NOTSET and logger_name:
                # the root logger's default WARNING threshold would
                # silence the INFO serving logs the ring exists to hold
                target.setLevel(logging.INFO)
        else:
            if stream is not None:
                _handler.stream = stream
            if worker is not None:
                _handler.worker = worker
        return _handler


def ring() -> LogRing:
    """The installed ring (installing record-only logging on demand)."""
    return install().ring


def set_worker(worker: int) -> None:
    """Stamp subsequent log entries with a pool worker index."""
    install(worker=worker)


def exposition_lines() -> List[str]:
    """``pio_tpu_log_messages_total`` exposition lines — registered as a
    collector by HTTP services so their ``/metrics`` carries log-volume
    counters without sharing a registry."""
    return _LOG_MESSAGES.render(pool=False)


# pio: endpoint=/logs.json
def logs_payload(n: int = 100, level: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 logger: Optional[str] = None) -> Dict[str, object]:
    """The ``GET /logs.json`` response body."""
    r = ring()
    entries = r.tail(n=n, level=level, trace_id=trace_id, logger=logger)
    return {
        "logs": [_public(e) for e in entries],
        "ringCapacity": r.cap,
        "dropped": r.dropped,
    }


def _reset_for_tests() -> None:
    """Detach the installed handler (test isolation only)."""
    global _handler
    with _install_lock:
        if _handler is not None:
            logging.getLogger("pio_tpu").removeHandler(_handler)
            _handler = None
