"""Aggregated hot-path latency budget: ``/debug/hotpath.json``.

Turns a path's stage histogram (``pio_tpu_<name>_stage_seconds``) plus
an end-to-end histogram into a per-stage budget: for each stage the
count, average, p50 and p95, and — the number the hot-path work is
judged against — the **attributed fraction**: how much of the average
end-to-end request the named top-level stages explain. BENCH_r05
measured p50 0.26 ms in-process against 1.17 ms end-to-end; this view
exists so that gap has named owners instead of being "host-side time".

Budget math: a stage's per-request cost is its total observed seconds
divided by the number of *requests* (not stage observations — a stage
that only runs for some requests is amortized over all of them, which
is what a budget means). Top-level stages (no ``.`` in the name) tile
the request and sum toward the attributed fraction; dotted substages
(``execute.device``, ``lock.*``, ``store.flush``) attribute time
*within* an enclosing stage and are reported but excluded from the sum
— counting both would attribute the same microseconds twice.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def _ms(v: Optional[float]) -> Optional[float]:
    return round(v * 1e3, 4) if v is not None else None


def _merged_stage_cells(hist) -> Dict[str, list]:
    """stage name -> cells (a tracer with extra labels, e.g. per-engine,
    has one cell per (labels, stage) combination)."""
    out: Dict[str, list] = {}
    for values, cell in list(hist._cells.items()):
        out.setdefault(values[-1], []).append(cell)
    return out


def _merge_snapshots(cells, pool: bool) -> Tuple[List[int], float, int]:
    buckets: List[int] = []
    total, count = 0.0, 0
    for cell in cells:
        b, s, c = cell._snapshot(pool)
        if not buckets:
            buckets = list(b)
        else:
            buckets = [x + y for x, y in zip(buckets, b)]
        total += s
        count += c
    return buckets, total, count


def _bucket_quantile(edges: Sequence[float], buckets: Sequence[int],
                     count: int, q: float) -> Optional[float]:
    """Same interpolation as ``_HistogramCell.quantile`` over an
    already-merged bucket vector."""
    if count == 0:
        return None
    rank = q * count
    cum = 0
    for k, c in enumerate(buckets):
        prev_cum = cum
        cum += c
        if cum >= rank and c > 0:
            lo = edges[k - 1] if k > 0 else 0.0
            if k >= len(edges):  # +Inf bucket
                return edges[-1] if edges else lo
            hi = edges[k]
            frac = (rank - prev_cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
    return edges[-1] if edges else None


# pio: endpoint=/debug/hotpath.json
def hotpath_payload(tracer, e2e_cell, stage_order: Sequence[str] = (),
                    pool: bool = True,
                    slow_threshold_s: Optional[float] = None) -> dict:
    """The ``/debug/hotpath.json`` body for one instrumented path.

    ``tracer`` supplies the per-stage histogram; ``e2e_cell`` is the
    end-to-end (accept→write) latency histogram cell the same requests
    were observed into. ``pool`` reads shm-aggregated values when the
    cells are bound (pool workers then all report the pool-wide budget).
    """
    hist = tracer.stage_histogram
    e2e_buckets, e2e_sum, e2e_count = e2e_cell._snapshot(pool)
    edges = e2e_cell._edges

    payload: dict = {
        "path": tracer.name,
        "requestCount": e2e_count,
        "e2e": {
            "avgMs": _ms(e2e_sum / e2e_count) if e2e_count else None,
            "p50Ms": _ms(_bucket_quantile(edges, e2e_buckets,
                                          e2e_count, 0.50)),
            "p95Ms": _ms(_bucket_quantile(edges, e2e_buckets,
                                          e2e_count, 0.95)),
        },
        "stages": [],
        "substages": [],
    }
    if slow_threshold_s is not None:
        payload["slowThresholdMs"] = _ms(slow_threshold_s)
    if hist is None:
        return payload

    by_stage = _merged_stage_cells(hist)
    order = [s for s in stage_order if s in by_stage]
    order += sorted(s for s in by_stage if s not in order)

    attributed_s = 0.0
    for stage in order:
        buckets, total, count = _merge_snapshots(by_stage[stage], pool)
        top_level = "." not in stage
        entry = {
            "stage": stage,
            "count": count,
            # budget: stage seconds amortized over REQUESTS, so stages
            # that run for a subset of requests still sum correctly
            "avgMs": _ms(total / e2e_count) if e2e_count else None,
            "p50Ms": _ms(_bucket_quantile(hist.buckets, buckets,
                                          count, 0.50)),
            "p95Ms": _ms(_bucket_quantile(hist.buckets, buckets,
                                          count, 0.95)),
        }
        if top_level and e2e_count:
            attributed_s += total / e2e_count
        payload["stages" if top_level else "substages"].append(entry)

    if e2e_count and e2e_sum > 0:
        e2e_avg = e2e_sum / e2e_count
        payload["attributedMsPerRequest"] = _ms(attributed_s)
        payload["attributedFraction"] = round(attributed_s / e2e_avg, 4)
        payload["residualMsPerRequest"] = _ms(e2e_avg - attributed_s)
        payload["residualFraction"] = round(
            1.0 - attributed_s / e2e_avg, 4
        )
    return payload
