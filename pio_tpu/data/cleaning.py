"""Event-store compaction — rebuild of the reference SelfCleaningDataSource.

Reference: ``core/src/main/scala/o/a/p/core/SelfCleaningDataSource.scala``
(UNVERIFIED path; SURVEY.md §2.1): a DataSource mix-in configured with an
``EventWindow(duration, removeDuplicates, compressProperties)`` that rewrites
the persisted event stream:

- ``duration`` — drop plain events whose ``event_time`` is older than
  ``now - duration``;
- ``compress_properties`` — fold each entity's ``$set/$unset/$delete`` chain
  into a single ``$set`` carrying the entity's final PropertyMap (entities
  whose final state is deleted disappear entirely);
- ``remove_duplicates`` — collapse events identical in everything but
  ``event_id``/``creation_time``.

The compaction itself reuses :mod:`pio_tpu.data.aggregation`'s fold (the
same semantics serving uses), so a compacted store aggregates identically to
the original — asserted by the test suite.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import re
from typing import List, Optional, Sequence, Tuple

from pio_tpu.data.aggregation import aggregate_properties
from pio_tpu.data.event import SPECIAL_EVENTS, Event

_DURATION_RE = re.compile(
    r"^\s*(\d+)\s*(seconds?|minutes?|hours?|days?|weeks?|s|m|h|d|w)\s*$",
    re.IGNORECASE,
)

_UNIT_SECONDS = {
    "s": 1, "second": 1, "seconds": 1,
    "m": 60, "minute": 60, "minutes": 60,
    "h": 3600, "hour": 3600, "hours": 3600,
    "d": 86400, "day": 86400, "days": 86400,
    "w": 604800, "week": 604800, "weeks": 604800,
}


def parse_duration(text: str) -> _dt.timedelta:
    """``"30 days"`` / ``"12h"`` / ``"90 minutes"`` → timedelta."""
    m = _DURATION_RE.match(text)
    if not m:
        raise ValueError(f"unparseable duration: {text!r}")
    value, unit = int(m.group(1)), m.group(2).lower()
    return _dt.timedelta(seconds=value * _UNIT_SECONDS[unit])


@dataclasses.dataclass(frozen=True)
class EventWindow:
    """≙ reference ``EventWindow`` case class."""

    duration: Optional[str] = None
    remove_duplicates: bool = False
    compress_properties: bool = False


def _dedup_key(e: Event) -> Tuple:
    import json

    return (
        e.event,
        e.entity_type,
        e.entity_id,
        e.target_entity_type,
        e.target_entity_id,
        # canonical JSON so list/dict property values stay hashable
        json.dumps(e.properties.to_dict(), sort_keys=True, default=str),
        e.event_time,
    )


def clean_events(
    events: Sequence[Event],
    window: EventWindow,
    now: Optional[_dt.datetime] = None,
) -> List[Event]:
    """Pure compaction: the cleaned event list for one (app, channel).

    Ordering of the result follows event time (stable for ties).
    """
    now = now or _dt.datetime.now(_dt.timezone.utc)
    ordered = sorted(events, key=lambda e: e.event_time)

    special = [e for e in ordered if e.event in SPECIAL_EVENTS]
    plain = [e for e in ordered if e.event not in SPECIAL_EVENTS]

    if window.duration is not None:
        cutoff = now - parse_duration(window.duration)
        plain = [e for e in plain if e.event_time >= cutoff]

    if window.compress_properties:
        folded = aggregate_properties(special)
        compressed = [
            Event(
                "$set",
                etype,
                eid,
                properties=pm.to_dict(),
                event_time=pm.last_updated,
            )
            for (etype, eid), pm in folded.items()
        ]
        special = sorted(compressed, key=lambda e: e.event_time)

    merged = sorted(special + plain, key=lambda e: e.event_time)

    if window.remove_duplicates:
        seen = set()
        deduped = []
        for e in merged:
            k = _dedup_key(e)
            if k not in seen:
                seen.add(k)
                deduped.append(e)
        merged = deduped

    return merged


class SelfCleaningDataSource:
    """DataSource mix-in: compact the persisted store in place.

    Subclasses (or callers) provide ``event_window`` — cleaning is a no-op
    without one — and call :meth:`clean_persisted_events` with the app id,
    typically right before ``read_training`` (the reference calls it from
    user DataSources the same way).
    """

    event_window: Optional[EventWindow] = None

    def clean_persisted_events(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        now: Optional[_dt.datetime] = None,
    ) -> int:
        """Rewrite the store; returns the number of events removed."""
        if self.event_window is None:
            return 0
        from pio_tpu.storage import Storage

        pe = Storage.get_pevents()
        before = pe.find(app_id, channel_id=channel_id)
        after = clean_events(before, self.event_window, now=now)

        # write-then-delete: a crash between the two calls leaves duplicates
        # (removable by a re-run), never a wiped store
        old_ids = [e.event_id for e in before if e.event_id]
        pe.write(
            [dataclasses.replace(e, event_id=None) for e in after],
            app_id,
            channel_id=channel_id,
        )
        pe.delete(old_ids, app_id, channel_id=channel_id)
        return len(before) - len(after)
