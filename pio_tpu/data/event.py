"""Canonical immutable Event + validation rules.

Rebuild of the reference's ``data/.../data/storage/Event.scala`` +
``EventValidation`` (UNVERIFIED path; see SURVEY.md provenance warning):
a time-stamped fact about an entity, optionally pointing at a target entity,
carrying a JSON property bag. Special events ``$set/$unset/$delete`` mutate
aggregated entity properties; names starting with ``$`` outside that set and
the ``pio_`` prefix on entity types / property keys are reserved.
"""

from __future__ import annotations

import datetime as _dt
import os
from dataclasses import dataclass, field, replace
from typing import Any, Mapping, Optional, Tuple

from pio_tpu.data.datamap import DataMap

#: Special events understood by the property-aggregation fold.
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})

#: Entity types reserved for internal use (reference: builtinEntityTypes).
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})

RESERVED_PREFIX = "pio_"


class EventValidationError(ValueError):
    """Raised when an event violates the validation rules."""


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


@dataclass(frozen=True)
class Event:
    """One immutable event.

    Field-for-field parity with the reference ``Event`` case class
    (eventId, event, entityType, entityId, targetEntityType, targetEntityId,
    properties, eventTime, tags, prId, creationTime).
    """

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: _dt.datetime = field(default_factory=_utcnow)
    tags: Tuple[str, ...] = ()
    pr_id: Optional[str] = None
    event_id: Optional[str] = None
    creation_time: _dt.datetime = field(default_factory=_utcnow)

    def __post_init__(self):
        # Normalize: naive datetimes are taken as UTC; times truncate to
        # millisecond precision (Joda-time parity — keeps in-memory values
        # identical to their wire/storage round-trip); properties may arrive
        # as a plain mapping.
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        for attr in ("event_time", "creation_time"):
            value = getattr(self, attr)
            if isinstance(value, _dt.datetime):
                if value.tzinfo is None:
                    value = value.replace(tzinfo=_dt.timezone.utc)
                value = value.replace(microsecond=value.microsecond // 1000 * 1000)
                object.__setattr__(self, attr, value)
        if isinstance(self.tags, list):
            object.__setattr__(self, "tags", tuple(self.tags))

    # -- helpers ------------------------------------------------------------
    def with_event_id(self, event_id: str) -> "Event":
        return replace(self, event_id=event_id)

    @staticmethod
    def new_event_id() -> str:
        # 128 random bits as 32 hex chars — the uuid4 wrapper's version-
        # bit bookkeeping cost ~5 µs/event on the ingest hot path for an
        # id that is opaque everywhere in the system
        return os.urandom(16).hex()

    # -- JSON (API wire format; reference EventJson4sSupport) ---------------
    def to_api_dict(self) -> dict:
        d: dict = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "properties": self.properties.to_dict(),
            "eventTime": _format_time(self.event_time),
            "creationTime": _format_time(self.creation_time),
        }
        if self.event_id is not None:
            d["eventId"] = self.event_id
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        if self.tags:
            d["tags"] = list(self.tags)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        return d

    @classmethod
    def from_api_dict(cls, d: Mapping[str, Any]) -> "Event":
        """Parse the Event-Server wire format (camelCase keys)."""
        props = d.get("properties")
        if props is None:
            props = {}
        if not isinstance(props, Mapping):
            raise EventValidationError("'properties' must be a JSON object")
        tags = d.get("tags")
        if tags is None:
            tags = ()
        if not isinstance(tags, (list, tuple)) or not all(
            isinstance(t, str) for t in tags
        ):
            raise EventValidationError("'tags' must be a list of strings")
        now = _utcnow()
        ev = cls(
            event=_req_str(d, "event"),
            entity_type=_req_str(d, "entityType"),
            entity_id=_req_str(d, "entityId"),
            target_entity_type=_opt_str(d, "targetEntityType"),
            target_entity_id=_opt_str(d, "targetEntityId"),
            properties=DataMap(props),
            event_time=_parse_time(d.get("eventTime")) or now,
            tags=tuple(tags),
            pr_id=_opt_str(d, "prId"),
            event_id=_opt_str(d, "eventId"),
            creation_time=_parse_time(d.get("creationTime")) or now,
        )
        validate_event(ev)
        return ev


def _req_str(d: Mapping[str, Any], key: str) -> str:
    if key not in d:
        raise EventValidationError(f"field {key!r} is required")
    v = d[key]
    if not isinstance(v, str):
        raise EventValidationError(f"field {key!r} must be a string")
    return v


def _opt_str(d: Mapping[str, Any], key: str) -> Optional[str]:
    v = d.get(key)
    if v is None:
        return None
    if not isinstance(v, str):
        raise EventValidationError(f"field {key!r} must be a string")
    return v


def _parse_time(s: Optional[str]) -> Optional[_dt.datetime]:
    if s is None:
        return None
    if not isinstance(s, str):
        raise EventValidationError("time fields must be ISO-8601 strings")
    try:
        # Accept ISO-8601, incl. trailing 'Z'.
        t = _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except ValueError as e:
        raise EventValidationError(f"cannot parse time {s!r}: {e}") from None
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return t


def _format_time(t: _dt.datetime) -> str:
    return t.astimezone(_dt.timezone.utc).isoformat(timespec="milliseconds").replace(
        "+00:00", "Z"
    )


def validate_event(e: Event) -> None:
    """Validation rules mirroring the reference ``EventValidation.validate``.

    - event / entityType / entityId non-empty
    - targetEntityType and targetEntityId specified together, non-empty
    - ``$``-prefixed events restricted to :data:`SPECIAL_EVENTS`
    - special-event rules: no target entity; ``$unset`` needs non-empty
      properties; ``$delete`` must carry no properties
    - ``pio_`` prefix reserved on entity types / property keys (except
      builtin types)
    """
    if not e.event:
        raise EventValidationError("event must not be empty")
    if not e.entity_type:
        raise EventValidationError("entityType must not be empty")
    if not e.entity_id:
        raise EventValidationError("entityId must not be empty")
    if e.target_entity_type is not None and not e.target_entity_type:
        raise EventValidationError("targetEntityType must not be empty string")
    if e.target_entity_id is not None and not e.target_entity_id:
        raise EventValidationError("targetEntityId must not be empty string")
    if (e.target_entity_type is None) != (e.target_entity_id is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be specified together"
        )
    if e.entity_type.startswith(RESERVED_PREFIX) and e.entity_type not in BUILTIN_ENTITY_TYPES:
        raise EventValidationError(
            f"entityType prefix {RESERVED_PREFIX!r} is reserved"
        )
    if e.target_entity_type is not None and e.target_entity_type.startswith(
        RESERVED_PREFIX
    ) and e.target_entity_type not in BUILTIN_ENTITY_TYPES:
        raise EventValidationError(
            f"targetEntityType prefix {RESERVED_PREFIX!r} is reserved"
        )
    for key in e.properties.keys():
        if key.startswith(RESERVED_PREFIX) or key.startswith("$"):
            raise EventValidationError(
                f"property key {key!r} uses a reserved prefix"
            )
    if e.event.startswith("$"):
        if e.event not in SPECIAL_EVENTS:
            raise EventValidationError(
                f"event name {e.event!r}: '$'-prefixed names are reserved "
                f"(allowed: {sorted(SPECIAL_EVENTS)})"
            )
        _validate_special(e)


def _validate_special(e: Event) -> None:
    if e.target_entity_type is not None or e.target_entity_id is not None:
        raise EventValidationError(
            f"special event {e.event} must not have targetEntity"
        )
    if e.event == "$unset" and e.properties.is_empty:
        raise EventValidationError("$unset event must have non-empty properties")
    if e.event == "$delete" and not e.properties.is_empty:
        raise EventValidationError("$delete event must not have properties")
