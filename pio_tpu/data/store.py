"""User-facing event store facades — stable API over the storage SPI.

Rebuild of the reference's ``data/src/main/scala/o/a/p/data/store/
{PEventStore,LEventStore,Common}.scala`` (UNVERIFIED paths; SURVEY.md §2.2
"Store facades"): engine code addresses apps by NAME (+ optional channel
name), the facade resolves names against the meta store and forwards to the
configured backend. ``PEventStore`` is the bulk/training side — its
``find`` returns a columnar :class:`EventFrame` ready for device transfer
(the reference returns an ``RDD[Event]``); ``LEventStore`` is the serving
side returning ``Event`` lists. Both are synchronous: the reference's
future/timeout machinery wrapped network storage clients, which this
framework's local backends don't need.
"""

from __future__ import annotations

import datetime as _dt
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

from pio_tpu.data.datamap import PropertyMap
from pio_tpu.data.event import Event

if TYPE_CHECKING:  # import cycle: pio_tpu.storage.base imports pio_tpu.data
    from pio_tpu.storage.frame import EventFrame


def _storage():
    """Deferred registry import — pio_tpu.storage imports pio_tpu.data at
    module load, so a top-level import here would be circular."""
    from pio_tpu.storage.registry import Storage

    return Storage


def resolve_channel(app_id: int, channel_name: Optional[str]) -> Optional[int]:
    """channel_id from its name within an app; None = default channel.

    The single home for channel lookup — the CLI and template helpers
    delegate here rather than re-implementing the meta-store query.
    """
    if not channel_name:
        return None
    chans = _storage().get_meta_data_channels().get_by_app_id(app_id)
    match = [c for c in chans if c.name == channel_name]
    if not match:
        raise ValueError(f"channel {channel_name!r} not found")
    return match[0].id


def resolve_names(
    app_name: str, channel_name: Optional[str] = None
) -> Tuple[int, Optional[int]]:
    """(app_id, channel_id) from names (reference ``Common.appNameToId``).

    ``channel_name`` None → the app's default channel (channel_id None).
    """
    app = _storage().get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise ValueError(f"app {app_name!r} not found")
    return app.id, resolve_channel(app.id, channel_name)


class PEventStore:
    """Bulk (training-side) reads — reference ``PEventStore`` object."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
    ) -> EventFrame:
        """Filtered scan → columnar frame (reference returns RDD[Event])."""
        app_id, channel_id = resolve_names(app_name, channel_name)
        return _storage().get_pevents().find_frame(
            app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )

    @staticmethod
    def find_events(
        app_name: str, channel_name: Optional[str] = None, **filters
    ) -> List[Event]:
        """Same filters as :meth:`find`, materialized as Event objects."""
        app_id, channel_id = resolve_names(app_name, channel_name)
        return _storage().get_pevents().find(
            app_id, channel_id=channel_id, **filters
        )

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """{entity_id: PropertyMap} from the entity's $set/$unset/$delete
        stream (reference ``PEventStore.aggregateProperties``)."""
        app_id, channel_id = resolve_names(app_name, channel_name)
        return _storage().get_pevents().aggregate_properties(
            app_id,
            entity_type,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )


class LEventStore:
    """Low-latency (serving-side) reads — reference ``LEventStore``."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = True,
    ) -> List[Event]:
        """Newest-first by default, as the serving path wants recency."""
        app_id, channel_id = resolve_names(app_name, channel_name)
        return _storage().get_levents().find(
            app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed_order=reversed_order,
        )

    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> List[Event]:
        """One entity's recent events (reference
        ``LEventStore.findByEntity``) — e.g. a user's last N interactions
        fetched inside ``Algorithm.predict`` for real-time re-ranking."""
        return LEventStore.find(
            app_name,
            channel_name=channel_name,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            limit=limit,
            reversed_order=latest,
        )
