"""BiMap — immutable bidirectional map.

Rebuild of the reference's ``data/.../data/storage/BiMap.scala`` (UNVERIFIED
path; see SURVEY.md). The main use is indexing string entity ids into dense
integer ids for matrix-factorization models (``BiMap.stringLong`` /
``stringInt`` in the reference). Unlike the reference — where the index
assignment order comes from RDD partition order — we assign indices over
**sorted** keys so index maps are deterministic and reproducible across runs.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, Iterator, Mapping, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    """Immutable one-to-one mapping with O(1) lookups both ways."""

    __slots__ = ("_fwd", "_rev")

    def __init__(self, mapping: Mapping[K, V], _rev: Optional[Dict[V, K]] = None):
        self._fwd: Dict[K, V] = dict(mapping)
        if _rev is None:
            _rev = {v: k for k, v in self._fwd.items()}
            if len(_rev) != len(self._fwd):
                raise ValueError("BiMap values must be unique")
        self._rev: Dict[V, K] = _rev

    # -- lookups ------------------------------------------------------------
    def __getitem__(self, key: K) -> V:
        return self._fwd[key]

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        return self._fwd.get(key, default)

    def __contains__(self, key: object) -> bool:
        return key in self._fwd

    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self) -> Iterator[K]:
        return iter(self._fwd)

    def keys(self):
        return self._fwd.keys()

    def values(self):
        return self._fwd.values()

    def items(self):
        return self._fwd.items()

    @property
    def inverse(self) -> "BiMap[V, K]":
        """Flipped view (reference ``BiMap.inverse``) — O(1): BiMap is
        immutable, so the view shares both dicts instead of copying them
        (a 59k-item catalog copy was ~40% of serving's per-request CPU)."""
        inv = BiMap.__new__(BiMap)
        inv._fwd = self._rev
        inv._rev = self._fwd
        return inv

    def to_dict(self) -> Dict[K, V]:
        return dict(self._fwd)

    def take(self, n: int) -> "BiMap[K, V]":
        sub = dict(list(self._fwd.items())[:n])
        return BiMap(sub)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BiMap):
            return self._fwd == other._fwd
        return NotImplemented

    def __repr__(self) -> str:
        return f"BiMap({self._fwd!r})"

    # -- constructors (reference stringInt / stringLong) --------------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Index distinct keys 0..n-1. Keys are sorted first for determinism
        (deviation from the reference's RDD-order assignment, documented)."""
        distinct = sorted(set(keys))
        return BiMap({k: i for i, k in enumerate(distinct)})

    @staticmethod
    def string_int_by_frequency(keys: Iterable[str]) -> "BiMap[str, int]":
        """Index distinct keys 0..n-1 by DESCENDING occurrence count
        (ties lexicographic, so the assignment stays deterministic).

        The TPU-aware index for interaction data: popular entities get
        low codes, which (a) clusters the hot rows of factor/embedding
        tables — better cache behavior for the training gathers and the
        serving scorer — and (b) makes the ALS delta item wire denser
        (most within-user gaps land among the small ids). Semantically
        interchangeable with :meth:`string_int`; only the code
        assignment differs.
        """
        from collections import Counter

        counts = Counter(keys)
        ordered = sorted(counts, key=lambda k: (-counts[k], k))
        return BiMap({k: i for i, k in enumerate(ordered)})

    # The reference distinguishes Int vs Long indices (JVM); in Python both
    # are `int`, so stringLong is an alias kept for API parity.
    string_long = string_int
