"""DataMap / PropertyMap — typed JSON property bags.

Rebuild of the reference's ``data/.../data/storage/DataMap.scala`` and
``PropertyMap.scala`` (UNVERIFIED paths; see SURVEY.md). A ``DataMap`` wraps a
JSON object; ``get`` raises on a missing key, ``get_opt`` returns ``None``.
``PropertyMap`` adds the aggregation timestamps ``first_updated`` /
``last_updated`` produced by folding ``$set/$unset/$delete`` event streams.
"""

from __future__ import annotations

import copy as _copy
import datetime as _dt
import json
from typing import Any, Iterable, Iterator, Mapping, Optional, Type, TypeVar

T = TypeVar("T")

# JSON scalar/compound types a DataMap value may hold.
JsonValue = Any


class DataMapError(KeyError):
    """Raised when a required field is missing or has the wrong type."""


def _json_copy(v: JsonValue) -> JsonValue:
    """Deep copy specialized to JSON trees (dict/list/tuple containers,
    immutable leaves shared). ~10× faster than ``copy.deepcopy`` — which
    was a measurable slice of per-event ingest cost — while keeping the
    same isolation guarantee for JSON-shaped input; anything exotic
    falls back to deepcopy."""
    t = type(v)
    if t is dict:
        return {k: _json_copy(x) for k, x in v.items()}
    if t is list:
        return [_json_copy(x) for x in v]
    if t is tuple:
        return tuple(_json_copy(x) for x in v)
    if t in (str, int, float, bool) or v is None:
        return v
    return _copy.deepcopy(v)


def _check_type(name: str, value: JsonValue, expected: Optional[Type]) -> JsonValue:
    if expected is None:
        return value
    if expected is float and isinstance(value, int) and not isinstance(value, bool):
        return float(value)  # JSON ints coerce up to float on request
    if expected is int and isinstance(value, bool):
        raise DataMapError(f"field {name!r} is a bool, expected {expected.__name__}")
    if not isinstance(value, expected):
        raise DataMapError(
            f"field {name!r} has type {type(value).__name__}, "
            f"expected {expected.__name__}"
        )
    return value


class DataMap:
    """Immutable typed view over a JSON object.

    Mirrors the reference API surface: ``get[T]`` -> :meth:`get`,
    ``getOpt[T]`` -> :meth:`get_opt`, ``getOrElse`` -> :meth:`get_or_else`,
    ``++`` -> :meth:`union`, ``--`` -> :meth:`minus`, ``keySet`` ->
    :meth:`keys`.

    Deliberately NOT a ``collections.abc.Mapping``: :meth:`get` follows the
    reference's required-typed-get contract (missing key raises; second arg
    is a type), which conflicts with ``Mapping.get``'s default-value
    contract — registering as a Mapping would invite generic dict code to
    misuse it.
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, JsonValue]] = None):
        # Deep-copy once at construction so outside mutation of the source
        # dict can't reach us. Decode hot paths that own their freshly
        # parsed dict should use :meth:`_wrap` instead.
        # no throwaway dict(fields) before the deep copy: _json_copy
        # already copies the top level when fields is a plain dict
        self._fields: dict = (
            _json_copy(fields if type(fields) is dict else dict(fields))
            if fields else {}
        )

    @classmethod
    def _wrap(cls, owned: dict) -> "DataMap":
        """No-copy constructor for callers handing over ownership of a
        never-aliased dict (e.g. a fresh ``json.loads`` result on storage
        decode paths)."""
        self = cls.__new__(cls)
        self._fields = owned
        return self

    # -- Mapping protocol ---------------------------------------------------
    def __getitem__(self, key: str) -> JsonValue:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def keys(self):
        return self._fields.keys()

    def values(self):
        return self._fields.values()

    def items(self):
        return self._fields.items()

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            # A plain DataMap never equals a PropertyMap (whose identity
            # includes timestamps) — keeps == transitive.
            return False
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- typed accessors ----------------------------------------------------
    def require(self, name: str) -> None:
        if name not in self._fields:
            raise DataMapError(f"The field {name!r} is required.")

    def get(self, name: str, typ: Optional[Type[T]] = None) -> T:  # type: ignore[override]
        """Mandatory typed get — raises :class:`DataMapError` if absent/null.

        Container values come back as copies so callers can't mutate the
        (immutable) map through them.
        """
        self.require(name)
        value = self._fields[name]
        if value is None:
            raise DataMapError(f"The required field {name!r} cannot be null.")
        value = _check_type(name, value, typ)
        # Containers come back as copies so callers can't mutate the map
        # (hash stability); scalar gets — the common case — stay copy-free.
        return _json_copy(value) if isinstance(value, (list, dict)) else value

    def get_opt(self, name: str, typ: Optional[Type[T]] = None) -> Optional[T]:
        value = self._fields.get(name)
        if value is None:
            return None
        value = _check_type(name, value, typ)
        return _json_copy(value) if isinstance(value, (list, dict)) else value

    def get_or_else(self, name: str, default: T, typ: Optional[Type[T]] = None) -> T:
        value = self.get_opt(name, typ)
        return default if value is None else value

    def get_double(self, name: str) -> float:
        return self.get(name, float)

    def get_string(self, name: str) -> str:
        return self.get(name, str)

    def get_string_list(self, name: str) -> list:
        value = self.get(name, list)
        if not all(isinstance(v, str) for v in value):
            raise DataMapError(f"field {name!r} is not a list of strings")
        return value

    # -- set algebra (reference ``++`` / ``--``) ----------------------------
    def union(self, other: "DataMap | Mapping[str, JsonValue]") -> "DataMap":
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def minus(self, keys: Iterable[str]) -> "DataMap":
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    # -- JSON ---------------------------------------------------------------
    def to_dict(self) -> dict:
        """Shallow copy — hot paths (EventFrame) read it without per-row
        deep copies; callers must not mutate nested containers."""
        return dict(self._fields)

    def to_json(self) -> str:
        return json.dumps(self._fields, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "DataMap":
        obj = json.loads(s)
        if not isinstance(obj, dict):
            raise DataMapError("DataMap JSON must be an object")
        return cls(obj)

    @property
    def is_empty(self) -> bool:
        return not self._fields


class PropertyMap(DataMap):
    """A DataMap plus aggregation timestamps.

    Produced by folding an entity's ``$set/$unset/$delete`` event stream
    (reference ``PropertyMap.scala`` + ``LEventAggregator.scala``):
    ``first_updated`` is the event time of the first event since the last
    ``$delete``; ``last_updated`` the latest event time folded in.
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, JsonValue]],
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self._fields!r}, first_updated={self.first_updated}, "
            f"last_updated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self._fields == other._fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        # Never equal to a plain DataMap/dict: delegating to field-only
        # equality would make == non-transitive across PropertyMaps with
        # different timestamps. Must be False, not NotImplemented — the
        # reflected DataMap.__eq__ would otherwise field-compare.
        return False

    __hash__ = DataMap.__hash__
