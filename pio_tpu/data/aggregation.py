"""Property aggregation: fold $set/$unset/$delete streams into PropertyMaps.

Rebuild of the reference's ``data/.../data/storage/LEventAggregator.scala`` /
``PEventAggregator.scala`` (UNVERIFIED paths; see SURVEY.md). Semantics:

- events are folded in ascending ``event_time`` order (ties broken by
  insertion order, i.e. a stable sort);
- ``$set``    merges the event's properties over the current state
  (later event time wins per key);
- ``$unset``  removes the named keys;
- ``$delete`` clears the entity entirely — both the properties and the
  ``first_updated`` watermark restart at the next ``$set``;
- entities whose final state is deleted (or never ``$set``) yield no entry.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterable, Optional, Tuple

from pio_tpu.data.datamap import PropertyMap
from pio_tpu.data.event import SPECIAL_EVENTS, Event


class _PropState:
    """Mutable fold state for one entity (reference ``Prop`` case class)."""

    __slots__ = ("fields", "first_updated", "last_updated")

    def __init__(self):
        self.fields: Optional[dict] = None
        self.first_updated: Optional[_dt.datetime] = None
        self.last_updated: Optional[_dt.datetime] = None

    def step(self, e: Event) -> None:
        if e.event == "$set":
            if self.fields is None:
                self.fields = e.properties.to_dict()
                self.first_updated = e.event_time
            else:
                self.fields.update(e.properties.to_dict())
            self.last_updated = e.event_time
        elif e.event == "$unset":
            if self.fields is not None:
                for k in e.properties.keys():
                    self.fields.pop(k, None)
                self.last_updated = e.event_time
        elif e.event == "$delete":
            self.fields = None
            self.first_updated = None
            self.last_updated = None

    def result(self) -> Optional[PropertyMap]:
        if self.fields is None:
            return None
        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(self.fields, self.first_updated, self.last_updated)


def fold_properties(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Fold one entity's special-event stream into its current PropertyMap."""
    ordered = sorted(events, key=lambda e: e.event_time)
    state = _PropState()
    for e in ordered:
        state.step(e)
    return state.result()


def aggregate_properties(
    events: Iterable[Event],
) -> Dict[Tuple[str, str], PropertyMap]:
    """Group special events by (entity_type, entity_id) and fold each group.

    Reference ``LEventAggregator.aggregateProperties``. Non-special events
    are ignored (callers normally pre-filter on event name).
    """
    groups: Dict[Tuple[str, str], list] = {}
    for e in events:
        if e.event in SPECIAL_EVENTS:
            groups.setdefault((e.entity_type, e.entity_id), []).append(e)
    out: Dict[Tuple[str, str], PropertyMap] = {}
    for key, evs in groups.items():
        pm = fold_properties(evs)
        if pm is not None:
            out[key] = pm
    return out
