"""Event data model: Event, DataMap, PropertyMap, BiMap, aggregation.

Rebuild of the reference's ``data/src/main/scala/o/a/p/data/storage/``
event model (Event.scala, DataMap.scala, PropertyMap.scala, BiMap.scala,
LEventAggregator.scala — paths UNVERIFIED, reference mount was empty; see
SURVEY.md provenance warning).
"""

from pio_tpu.data.datamap import DataMap, PropertyMap
from pio_tpu.data.event import Event, EventValidationError, validate_event
from pio_tpu.data.bimap import BiMap
from pio_tpu.data.aggregation import aggregate_properties, fold_properties
from pio_tpu.data.cleaning import (
    EventWindow,
    SelfCleaningDataSource,
    clean_events,
    parse_duration,
)
from pio_tpu.data.store import LEventStore, PEventStore

__all__ = [
    "DataMap",
    "PropertyMap",
    "Event",
    "EventValidationError",
    "validate_event",
    "BiMap",
    "aggregate_properties",
    "fold_properties",
    "EventWindow",
    "SelfCleaningDataSource",
    "clean_events",
    "parse_duration",
    "LEventStore",
    "PEventStore",
]
