// Native COO→blocked-CSR packer — the ALS data-loader hot path.
//
// The reference's training reads ride Spark RDD shuffles; this framework
// packs rating edges into dense [n_blocks, width] blocks on the host
// before one coalesced transfer to the TPU (pio_tpu/models/als.py
// _pack_blocks documents the layout: blocks sorted by entity id, padded
// slots carry other = -1). The numpy implementation is a single-threaded
// argsort + scatter (~1s per 2M edges); this one is a stable parallel
// counting sort writing straight into the caller's transfer buffers.
//
// Exposed via a C ABI consumed with ctypes (pio_tpu/native/__init__.py
// builds this file with g++ on first use).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

namespace {

int n_threads(int64_t n_edges, int32_t n_entities) {
  unsigned hw = std::thread::hardware_concurrency();
  int t = static_cast<int>(hw ? hw : 4);
  t = std::min(t, 16);
  // under ~1M edges the spawn cost outweighs the split
  if (n_edges < (1 << 20)) t = 1;
  // per-thread histograms cost T * n_entities * 8 bytes — cap the total
  // at ~256 MB so a huge sparse catalog can't trigger a multi-GB spike
  int64_t mem_cap = (256LL << 20) / (8 * std::max<int64_t>(1, n_entities));
  t = static_cast<int>(std::min<int64_t>(t, std::max<int64_t>(1, mem_cap)));
  return std::max(1, t);
}

template <typename F>
void parallel_ranges(int64_t n, int threads, F&& fn) {
  if (threads == 1) {
    fn(0, int64_t{0}, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([&fn, t, lo, hi] { fn(t, lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Pass 1: per-entity degree histogram → counts[n_entities], and the total
// block count at the given width. Returns n_blocks, or -1 on bad input
// (an entity id outside [0, n_entities)).
int64_t als_pack_count(const int32_t* ent, int64_t n_edges,
                       int32_t n_entities, int32_t width,
                       int64_t* counts) {
  std::memset(counts, 0, sizeof(int64_t) * n_entities);
  const int T = n_threads(n_edges, n_entities);
  std::atomic<bool> ok{true};
  if (T == 1) {
    for (int64_t k = 0; k < n_edges; ++k) {
      int32_t e = ent[k];
      if (e < 0 || e >= n_entities) return -1;
      ++counts[e];
    }
  } else {
    std::vector<std::vector<int64_t>> part(
        T, std::vector<int64_t>(n_entities, 0));
    parallel_ranges(n_edges, T, [&](int t, int64_t lo, int64_t hi) {
      auto& h = part[t];
      for (int64_t k = lo; k < hi; ++k) {
        int32_t e = ent[k];
        if (e < 0 || e >= n_entities) {
          ok.store(false, std::memory_order_relaxed);
          return;
        }
        ++h[e];
      }
    });
    if (!ok.load()) return -1;
    for (int t = 0; t < T; ++t)
      for (int32_t e = 0; e < n_entities; ++e) counts[e] += part[t][e];
  }
  int64_t n_blocks = 0;
  for (int32_t e = 0; e < n_entities; ++e)
    n_blocks += (counts[e] + width - 1) / width;
  return n_blocks;
}

// Pass 2: stable scatter into the caller-allocated block arrays
// (block_ent [S], block_other [S*width], block_rating [S*width] — the
// caller may point these INTO its coalesced transfer buffers). counts is
// pass 1's output; S is the padded block count (≥ n_blocks). Edge order
// within an entity is preserved (stable, like the numpy argsort path).
// Returns 0.
int als_pack_fill(const int32_t* ent, const int32_t* other,
                  const float* rating, int64_t n_edges, int32_t n_entities,
                  int32_t width, const int64_t* counts, int64_t S,
                  int32_t* block_ent, int32_t* block_other,
                  float* block_rating) {
  const int T = n_threads(n_edges, n_entities);

  // entity → first flat slot of its first block
  std::vector<int64_t> slot_start(n_entities + 1);
  slot_start[0] = 0;
  for (int32_t e = 0; e < n_entities; ++e) {
    int64_t blocks = (counts[e] + width - 1) / width;
    slot_start[e + 1] = slot_start[e] + blocks * width;
  }

  // per-(thread, entity) write cursors: thread t starts after all edges
  // of the same entity owned by threads < t → stable by construction
  std::vector<std::vector<int64_t>> cursor(
      T, std::vector<int64_t>(n_entities, 0));
  if (T > 1) {
    parallel_ranges(n_edges, T, [&](int t, int64_t lo, int64_t hi) {
      auto& h = cursor[t];
      for (int64_t k = lo; k < hi; ++k) ++h[ent[k]];
    });
    // exclusive scan over threads per entity
    for (int32_t e = 0; e < n_entities; ++e) {
      int64_t acc = 0;
      for (int t = 0; t < T; ++t) {
        int64_t c = cursor[t][e];
        cursor[t][e] = acc;
        acc += c;
      }
    }
  }

  const int64_t total = S * static_cast<int64_t>(width);
  parallel_ranges(total, T, [&](int, int64_t lo, int64_t hi) {
    std::fill(block_other + lo, block_other + hi, int32_t{-1});
    std::memset(block_rating + lo, 0, sizeof(float) * (hi - lo));
  });

  parallel_ranges(n_edges, T, [&](int t, int64_t lo, int64_t hi) {
    auto& cur = cursor[t];
    for (int64_t k = lo; k < hi; ++k) {
      int32_t e = ent[k];
      int64_t pos = cur[e]++;
      // position → flat slot: whole blocks are width apart
      int64_t flat = slot_start[e] + pos;
      block_other[flat] = other[k];
      block_rating[flat] = rating[k];
    }
  });

  // block_ent: entity of each block, ascending; padding blocks point at
  // the last entity (their slots are all masked)
  std::vector<int64_t> block_start(n_entities + 1);
  block_start[0] = 0;
  for (int32_t e = 0; e < n_entities; ++e)
    block_start[e + 1] = block_start[e] + (counts[e] + width - 1) / width;
  parallel_ranges(n_entities, T, [&](int, int64_t lo, int64_t hi) {
    for (int64_t e = lo; e < hi; ++e)
      for (int64_t s = block_start[e]; s < block_start[e + 1]; ++s)
        block_ent[s] = static_cast<int32_t>(e);
  });
  for (int64_t s = block_start[n_entities]; s < S; ++s)
    block_ent[s] = n_entities - 1;
  return 0;
}

// Stable counting sort of (other, rating) by entity id — the wire-format
// reducer for the single-device path: once edges are entity-sorted, the
// per-edge entity plane collapses to a per-entity COUNTS array (65k× fewer
// bytes at MovieLens scale) and the device rebuilds ids with one repeat.
// counts is als_pack_count's output. Returns 0.
//
// Two-level scatter: a direct counting-sort scatter is TLB-miss bound
// (25M random 8 B writes across a 200 MB destination ≈ 35 ns each).
// Pass 1 partitions edges into ≤256 coarse buckets of contiguous entity
// ranges (≤256 active write streams — TLB-resident); pass 2 scatters
// each bucket internally, where the destination range is ~1 MB and
// cache-resident. Both passes are stable (edges keep arrival order per
// thread, threads are rank-ordered per bucket/entity), so the result
// matches a stable sort by entity exactly. Measured ~2× faster than the
// direct scatter at MovieLens-25M scale on one core.
int als_sort_by_entity(const int32_t* ent, const int32_t* other,
                       const float* rating, int64_t n_edges,
                       int32_t n_entities, const int64_t* counts,
                       int32_t* other_sorted, float* rating_sorted) {
  const int T = n_threads(n_edges, n_entities);

  std::vector<int64_t> edge_start(n_entities + 1);
  edge_start[0] = 0;
  for (int32_t e = 0; e < n_entities; ++e)
    edge_start[e + 1] = edge_start[e] + counts[e];

  // bucket = entity >> shift, sized so bucket count ≤ 256
  int shift = 0;
  while ((static_cast<int64_t>(n_entities - 1) >> shift) >= 256) ++shift;
  const int B = static_cast<int>(((n_entities - 1) >> shift) + 1);
  std::vector<int64_t> bucket_start(B + 1);
  for (int b = 0; b < B; ++b)
    bucket_start[b] = edge_start[std::min<int64_t>(
        static_cast<int64_t>(b) << shift, n_entities)];
  bucket_start[B] = n_edges;

  // per-(thread, bucket) cursors, stable by thread order
  std::vector<std::vector<int64_t>> bcur(T, std::vector<int64_t>(B, 0));
  if (T > 1) {
    parallel_ranges(n_edges, T, [&](int t, int64_t lo, int64_t hi) {
      auto& h = bcur[t];
      for (int64_t k = lo; k < hi; ++k) ++h[ent[k] >> shift];
    });
    for (int b = 0; b < B; ++b) {
      int64_t acc = 0;
      for (int t = 0; t < T; ++t) {
        int64_t c = bcur[t][b];
        bcur[t][b] = acc;
        acc += c;
      }
    }
  }

  // default-init scratch (every slot written exactly once)
  std::unique_ptr<uint64_t[]> packed(new uint64_t[n_edges]);
  std::unique_ptr<int32_t[]> ent_tmp(new int32_t[n_edges]);
  parallel_ranges(n_edges, T, [&](int t, int64_t lo, int64_t hi) {
    auto& cur = bcur[t];
    for (int64_t k = lo; k < hi; ++k) {
      int32_t e = ent[k];
      int64_t dst = bucket_start[e >> shift] + cur[e >> shift]++;
      uint32_t rbits;
      std::memcpy(&rbits, &rating[k], 4);
      ent_tmp[dst] = e;
      packed[dst] = (static_cast<uint64_t>(rbits) << 32) |
                    static_cast<uint32_t>(other[k]);
    }
  });

  // pass 2: buckets own disjoint entity ranges, so one global per-entity
  // cursor array has no cross-bucket races; parallel over buckets
  std::vector<int64_t> ecur(n_entities, 0);
  parallel_ranges(B, std::min(T, B), [&](int, int64_t blo, int64_t bhi) {
    for (int64_t b = blo; b < bhi; ++b) {
      for (int64_t k = bucket_start[b]; k < bucket_start[b + 1]; ++k) {
        int32_t e = ent_tmp[k];
        int64_t dst = edge_start[e] + ecur[e]++;
        uint64_t p = packed[k];
        other_sorted[dst] = static_cast<int32_t>(p & 0xFFFFFFFFu);
        uint32_t rbits = static_cast<uint32_t>(p >> 32);
        std::memcpy(&rating_sorted[dst], &rbits, 4);
      }
    }
  });
  return 0;
}

// Fused rating-wire classifier + encoder, one parallel pass: detects the
// half-star grid (every rating*2 a nonneg integer) and emits u8 codes.
// Returns the max code (0..510), or -1 if any rating is off-grid (caller
// falls back to f16/f32 encoding in numpy). Replaces a ~4-pass numpy
// pipeline on the pack hot path.
int64_t als_rating_codes(const float* rating, int64_t n_edges,
                         uint8_t* codes) {
  const int T = n_threads(n_edges, 1);
  std::vector<int64_t> maxes(T, 0);
  std::atomic<bool> ok{true};
  parallel_ranges(n_edges, T, [&](int t, int64_t lo, int64_t hi) {
    int64_t mx = 0;
    for (int64_t k = lo; k < hi; ++k) {
      float r2 = rating[k] * 2.0f;
      // range-guard BEFORE the int cast: float→int of NaN/inf/out-of-
      // range is UB (the guard also rejects NaN via negated compares)
      if (!(r2 >= 0.0f) || !(r2 <= 255.0f)) {
        ok.store(false, std::memory_order_relaxed);
        return;
      }
      int32_t v = static_cast<int32_t>(r2);
      if (static_cast<float>(v) != r2) {
        ok.store(false, std::memory_order_relaxed);
        return;
      }
      codes[k] = static_cast<uint8_t>(v);
      if (v > mx) mx = v;
    }
    maxes[t] = mx;
  });
  if (!ok.load()) return -1;
  int64_t mx = 0;
  for (int t = 0; t < T; ++t) mx = std::max(mx, maxes[t]);
  return mx;
}

// In-place stable sort of each entity's adjacency segment by the OTHER id
// (items ascending within a user). ALS is invariant to within-entity edge
// order, and the sorted adjacency is what makes the delta item wire
// (pio_tpu/models/als.py _encode_items_delta) dense: gaps between
// consecutive items fit 12 bits almost everywhere. Matches numpy's
// np.lexsort((other, ent)) order exactly: stable on duplicate ids.
//
// Implementation: per-segment LSD radix over the id bytes (digit count
// from the global max id — 2 passes at MovieLens scale), with a stable
// insertion sort for tiny segments. Radix is branchless where introsort
// on random ids mispredicts half its compares — measured ~2× faster at
// 25M edges / 154-edge average segments, and the id+rating pair moves
// together so there is no key-pack/unpack pass. counts is
// als_pack_count's output. Returns 0.
int als_sort_within_entity(int32_t* other_sorted, float* rating_sorted,
                           int32_t n_entities, const int64_t* counts) {
  int64_t n_edges = 0, max_seg = 0;
  for (int32_t e = 0; e < n_entities; ++e) {
    n_edges += counts[e];
    max_seg = std::max(max_seg, counts[e]);
  }
  // fail loud rather than let the uint32 radix cursors wrap silently
  if (max_seg >= (1LL << 32)) return -1;
  const int T = n_threads(n_edges, n_entities);

  std::vector<int64_t> edge_start(n_entities + 1);
  edge_start[0] = 0;
  for (int32_t e = 0; e < n_entities; ++e)
    edge_start[e + 1] = edge_start[e] + counts[e];

  // digit count for the radix from the global max id (sequential scan:
  // ~1 ns/edge, keeps every segment's pass count identical)
  int32_t max_id = 0;
  for (int64_t k = 0; k < n_edges; ++k)
    max_id = std::max(max_id, other_sorted[k]);
  // 64-bit shift + passes<=4 bound: a 32-bit shift by 32 (ids >= 2^24)
  // would be UB and, with x86 mod-32 semantics, an infinite loop
  int passes = 1;
  while (passes < 4 &&
         (static_cast<uint64_t>(static_cast<uint32_t>(max_id)) >>
          (8 * passes)) != 0)
    ++passes;

  parallel_ranges(n_entities, T, [&](int, int64_t lo, int64_t hi) {
    std::vector<int32_t> tmp_o;
    std::vector<float> tmp_r;
    uint32_t cnt[256];
    for (int64_t e = lo; e < hi; ++e) {
      int64_t s = edge_start[e], n = counts[e];
      if (n < 2) continue;
      int32_t* o = other_sorted + s;
      float* r = rating_sorted + s;
      if (n <= 24) {
        // stable insertion sort (shift only while strictly greater)
        for (int64_t k = 1; k < n; ++k) {
          int32_t ok = o[k];
          float rk = r[k];
          int64_t j = k - 1;
          while (j >= 0 && o[j] > ok) {
            o[j + 1] = o[j];
            r[j + 1] = r[j];
            --j;
          }
          o[j + 1] = ok;
          r[j + 1] = rk;
        }
        continue;
      }
      if (static_cast<int64_t>(tmp_o.size()) < n) {
        tmp_o.resize(n);
        tmp_r.resize(n);
      }
      int32_t* src_o = o;
      float* src_r = r;
      int32_t* dst_o = tmp_o.data();
      float* dst_r = tmp_r.data();
      for (int pass = 0; pass < passes; ++pass) {
        const int shift = 8 * pass;
        std::memset(cnt, 0, sizeof(cnt));
        for (int64_t k = 0; k < n; ++k)
          ++cnt[(static_cast<uint32_t>(src_o[k]) >> shift) & 0xFF];
        uint32_t acc = 0;
        for (int b = 0; b < 256; ++b) {
          uint32_t c = cnt[b];
          cnt[b] = acc;
          acc += c;
        }
        for (int64_t k = 0; k < n; ++k) {
          uint32_t pos =
              cnt[(static_cast<uint32_t>(src_o[k]) >> shift) & 0xFF]++;
          dst_o[pos] = src_o[k];
          dst_r[pos] = src_r[k];
        }
        std::swap(src_o, dst_o);
        std::swap(src_r, dst_r);
      }
      if (passes & 1) {  // result landed in the scratch: copy back
        std::memcpy(o, src_o, sizeof(int32_t) * n);
        std::memcpy(r, src_r, sizeof(float) * n);
      }
    }
  });
  return 0;
}

// 12-bit delta item wire over a (user, item)-sorted edge array — the
// native fast path for pio_tpu/models/als.py _encode_items_delta (the
// numpy fallback there defines the format). Pass 1 counts gaps ≥ 4096;
// pass 2 fills d_lo (u8 low byte), d_hi (high 4 bits nibble-packed, two
// edges per byte) and the sparse overflow (edge index, delta >> 12).
// counts segments the edges (zero entries allowed). Returns n_ovf, or
// -1 on a negative gap (input not item-sorted) or a gap ≥ 2^16.
int64_t als_delta_count(const int32_t* ids, const int64_t* counts,
                        int32_t n_segments) {
  int64_t n_ovf = 0, p = 0;
  for (int32_t s = 0; s < n_segments; ++s) {
    int32_t prev = 0;
    for (int64_t k = 0; k < counts[s]; ++k, ++p) {
      int64_t d = static_cast<int64_t>(ids[p]) - prev;
      if (d < 0 || d >= (1LL << 16)) return -1;
      if (d > 0xFFF) ++n_ovf;
      prev = ids[p];
    }
  }
  return n_ovf;
}

int als_delta_fill(const int32_t* ids, const int64_t* counts,
                   int32_t n_segments, int64_t n_edges,
                   uint8_t* d_lo, uint8_t* d_hi,
                   int32_t* ovf_idx, uint8_t* ovf_val) {
  std::memset(d_hi, 0, static_cast<size_t>((n_edges + 1) / 2));
  int64_t n_ovf = 0, p = 0;
  for (int32_t s = 0; s < n_segments; ++s) {
    int32_t prev = 0;
    for (int64_t k = 0; k < counts[s]; ++k, ++p) {
      int32_t d = ids[p] - prev;
      d_lo[p] = static_cast<uint8_t>(d & 0xFF);
      d_hi[p / 2] |= static_cast<uint8_t>(((d >> 8) & 0xF)
                                          << ((p % 2) ? 4 : 0));
      if (d > 0xFFF) {
        ovf_idx[n_ovf] = static_cast<int32_t>(p);
        ovf_val[n_ovf] = static_cast<uint8_t>(d >> 12);
        ++n_ovf;
      }
      prev = ids[p];
    }
  }
  return 0;
}

}  // extern "C"
