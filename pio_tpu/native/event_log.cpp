// Native event-log storage engine — append-only binary log + filtered scan.
//
// The reference's at-scale event store is HBase with a hand-designed rowkey
// (storage/hbase/.../HBEventsUtil.scala — UNVERIFIED path; SURVEY.md §2.3):
// a network KV store the JVM queries per scan. This framework's native
// equivalent is a local append-only record log per (app, channel) with the
// filter/sort/tombstone logic in C++, so the training-read hot path
// (PEvents.find_frame feeding DataSources) never loops over records in
// Python. Exposed via a C ABI consumed with ctypes
// (pio_tpu/native/__init__.py builds this file with g++ on first use).
//
// Record layout (little-endian), file = 8-byte magic "PEL2\0\0\0\0" then
// records:
//   u32  payload_len                  (bytes after this field, before crc)
//   u8   flags                        (bit0 = tombstone: event_id names the
//                                      record to delete)
//   i64  event_time_us
//   i64  creation_time_us
//   u16  len[8]: event_id, event, entity_type, entity_id,
//                target_entity_type, target_entity_id, pr_id, tags_json
//   u32  len_props_json
//   bytes: the 9 strings concatenated (utf-8)
//   u32  crc32 of the payload (zlib polynomial; v2 only)
//
// v1 files ("PEL1" magic, no per-record crc) remain readable; pel_repair
// upgrades them in place (atomic rewrite) before any v2-framed append.
// The crc turns "plausible-length garbage at the tail" — a torn write the
// length check alone can't see — into a detected torn tail, and garbage
// anywhere else into detected corruption instead of silently-wrong scans.

#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr char kMagicV1[8] = {'P', 'E', 'L', '1', 0, 0, 0, 0};
constexpr char kMagicV2[8] = {'P', 'E', 'L', '2', 0, 0, 0, 0};
constexpr int kNumStr = 9;  // 8 u16-length strings + props (u32 length)
constexpr size_t kHeaderFixed = 1 + 8 + 8 + 8 * 2 + 4;

// zlib-compatible CRC-32 (poly 0xEDB88320), so Python's zlib.crc32 frames
// records the scanner verifies without linking -lz into the .so.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

uint32_t crc32_feed(uint32_t crc, const char* data, size_t len) {
  static const Crc32Table tbl;
  for (size_t i = 0; i < len; ++i)
    crc = tbl.t[(crc ^ static_cast<uint8_t>(data[i])) & 0xFFu] ^ (crc >> 8);
  return crc;
}

uint32_t crc32_of(const char* data, size_t len) {
  return crc32_feed(0xFFFFFFFFu, data, len) ^ 0xFFFFFFFFu;
}

struct Rec {
  uint8_t flags;
  int64_t time_us;
  int64_t ctime_us;
  const char* str[kNumStr];
  uint32_t len[kNumStr];
  int64_t seq;  // file order, for a stable sort
};

template <typename T>
T read_le(const char* p) {
  T v;
  std::memcpy(&v, p, sizeof(T));
  return v;
}

// Last-write-wins filter shared by scan/count/compact: the newest record
// per event id (by file order) is authoritative; a tombstone as winner
// kills the id. Appends the surviving records to *live in file order.
void collect_live(const std::vector<Rec>& recs,
                  std::vector<const Rec*>* live) {
  std::unordered_map<std::string, int64_t> last;
  for (const Rec& r : recs) last[std::string(r.str[0], r.len[0])] = r.seq;
  for (const Rec& r : recs) {
    if (r.flags & 1) continue;
    if (last[std::string(r.str[0], r.len[0])] != r.seq) continue;
    live->push_back(&r);
  }
}

// Parses whole records. A *torn tail* — a trailing partial record left by a
// crash mid-append (the bytes are a prefix of one framed record) — is NOT
// corruption: parsing stops there and *valid_end marks the end of the last
// whole record, so committed data stays readable. A v2 record whose crc
// mismatches is a torn tail IF it is the final record (in-place garbage
// from a failed write), and corruption otherwise. Only mid-record
// inconsistencies (bad magic, lengths that disagree within fully-present
// bytes, a mid-file crc mismatch) return false.
// out may be null (framing/validation walk only — no Rec materialization;
// pel_repair uses this to find valid_end without O(records) memory).
// version_out (may be null) reports the file format: 1, or 2 (also for
// empty/absent files, which pel_append will create as v2).
bool parse_records(const std::vector<char>& buf, std::vector<Rec>* out,
                   size_t* valid_end, int* version_out = nullptr) {
  *valid_end = 0;
  int version = 2;
  if (buf.size() >= 8) {
    if (std::memcmp(buf.data(), kMagicV2, 8) == 0)
      version = 2;
    else if (std::memcmp(buf.data(), kMagicV1, 8) == 0)
      version = 1;
    else
      return false;
  }
  if (version_out) *version_out = version;
  if (buf.size() < 8) return true;  // empty or torn magic
  const size_t trailer = version == 2 ? 4 : 0;  // per-record crc32
  size_t pos = 8;
  *valid_end = pos;
  int64_t seq = 0;
  while (pos + 4 <= buf.size()) {
    uint32_t plen = read_le<uint32_t>(buf.data() + pos);
    if (plen < kHeaderFixed) return false;
    if (pos + 4 + plen + trailer > buf.size()) return true;  // torn tail
    const char* p = buf.data() + pos + 4;
    if (version == 2) {
      uint32_t want = read_le<uint32_t>(p + plen);
      if (crc32_of(p, plen) != want)
        // garbled final record = torn tail (truncate); earlier = corrupt
        return pos + 4 + plen + trailer == buf.size();
    }
    Rec r;
    r.flags = static_cast<uint8_t>(*p);
    r.time_us = read_le<int64_t>(p + 1);
    r.ctime_us = read_le<int64_t>(p + 9);
    size_t off = 17;
    uint64_t total = 0;
    for (int i = 0; i < kNumStr - 1; ++i) {
      r.len[i] = read_le<uint16_t>(p + off);
      off += 2;
      total += r.len[i];
    }
    r.len[kNumStr - 1] = read_le<uint32_t>(p + off);
    off += 4;
    total += r.len[kNumStr - 1];
    if (off + total != plen) return false;
    const char* s = p + off;
    for (int i = 0; i < kNumStr; ++i) {
      r.str[i] = s;
      s += r.len[i];
    }
    r.seq = seq++;
    if (out) out->push_back(r);
    pos += 4 + plen + trailer;
    *valid_end = pos;
  }
  return true;
}

// Writes magic + the given records re-framed as v2 (crc per record).
// Shared by pel_compact and pel_repair's v1 → v2 upgrade.
bool write_records_v2(FILE* f, const std::vector<const Rec*>& recs) {
  bool ok = std::fwrite(kMagicV2, 1, 8, f) == 8;
  for (const Rec* r : recs) {
    if (!ok) break;
    uint64_t payload = kHeaderFixed;
    for (int c = 0; c < kNumStr; ++c) payload += r->len[c];
    uint32_t plen = static_cast<uint32_t>(payload);
    char head[4 + kHeaderFixed];
    std::memcpy(head, &plen, 4);
    char* p = head + 4;
    p[0] = static_cast<char>(r->flags);
    std::memcpy(p + 1, &r->time_us, 8);
    std::memcpy(p + 9, &r->ctime_us, 8);
    size_t off = 17;
    for (int c = 0; c < kNumStr - 1; ++c) {
      uint16_t l16 = static_cast<uint16_t>(r->len[c]);
      std::memcpy(p + off, &l16, 2);
      off += 2;
    }
    std::memcpy(p + off, &r->len[kNumStr - 1], 4);
    uint32_t crc = crc32_feed(0xFFFFFFFFu, head + 4, kHeaderFixed);
    ok = std::fwrite(head, 1, sizeof(head), f) == sizeof(head);
    for (int c = 0; ok && c < kNumStr; ++c)
      if (r->len[c]) {
        ok = std::fwrite(r->str[c], 1, r->len[c], f) == r->len[c];
        crc = crc32_feed(crc, r->str[c], r->len[c]);
      }
    crc ^= 0xFFFFFFFFu;
    ok = ok && std::fwrite(&crc, 1, 4, f) == 4;
  }
  return ok;
}

bool read_file(const char* path, std::vector<char>* buf) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return errno == ENOENT;  // only an absent file is an empty log
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  buf->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(buf->data(), 1, buf->size(), f) : 0;
  std::fclose(f);
  return got == buf->size();
}

bool str_eq(const char* a, uint32_t alen, const char* b) {
  return std::strlen(b) == alen && std::memcmp(a, b, alen) == 0;
}

// filter string sets arrive as "name1\0name2\0" (count separately)
bool in_set(const char* s, uint32_t slen, const char* set, int count) {
  const char* p = set;
  for (int i = 0; i < count; ++i) {
    size_t l = std::strlen(p);
    if (l == slen && std::memcmp(p, s, l) == 0) return true;
    p += l + 1;
  }
  return false;
}

}  // namespace

extern "C" {

// Columnar scan result. String column i: chars arena[off[i][k]..off[i][k+1])
// for row k; off arrays have n+1 entries. Free with pel_free_result.
typedef struct {
  int64_t n;
  int64_t* time_us;
  int64_t* ctime_us;
  char* arena[kNumStr];
  uint32_t* off[kNumStr];
} PelResult;

void pel_free_result(PelResult* r);

// Appends pre-encoded record bytes (Python frames them, v2 with crc);
// creates the file with magic if needed. do_sync != 0 → fsync before
// close (the durability knob: "commit" always, "batch" on its interval).
// Returns 0 on success.
int pel_append(const char* path, const uint8_t* data, int64_t len,
               int do_sync) {
  FILE* f = std::fopen(path, "ab");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  if (std::ftell(f) == 0) {
    if (std::fwrite(kMagicV2, 1, sizeof(kMagicV2), f) !=
        sizeof(kMagicV2)) {
      std::fclose(f);
      return -1;
    }
  }
  size_t wrote = std::fwrite(data, 1, static_cast<size_t>(len), f);
  // fflush+fclose must BOTH succeed: stdio buffering means fwrite can
  // report full length while the actual write (ENOSPC, EIO) fails at
  // flush — returning 0 then would claim persistence that never happened
  bool flushed = std::fflush(f) == 0;
  bool synced = !do_sync || (flushed && fsync(fileno(f)) == 0);
  bool closed = std::fclose(f) == 0;
  return (wrote == static_cast<size_t>(len) && flushed && synced && closed)
             ? 0
             : -1;
}

// Filtered scan. Empty-string filters mean "any"; event_names is a packed
// set ("a\0b\0", event_name_count entries, 0 = any). start/until in
// microseconds (INT64_MIN/MAX = unbounded; until is exclusive).
// reversed != 0 → newest first. limit < 0 → no limit.
// event_id filter ("" = any) serves LEvents.get. (The Python wrapper maps
// explicit empty-string filters to "match nothing" before the ABI.)
// Returns 0 ok, -1 io error, -2 corrupt file, -3 result too large
// (a string column would overflow the u32 offset arrays), -4 out of
// memory. Never throws across the C ABI.
static int pel_scan_impl(const char* path, const char* event_names,
                         int event_name_count, const char* entity_type,
                         const char* entity_id,
                         const char* target_entity_type,
                         const char* target_entity_id, const char* event_id,
                         int64_t start_us, int64_t until_us, int reversed,
                         int64_t limit, PelResult* out) {
  std::vector<char> buf;
  if (!read_file(path, &buf)) return -1;
  std::vector<Rec> recs;
  size_t valid_end;
  if (!parse_records(buf, &recs, &valid_end)) return -2;

  // last-write-wins per event_id (collect_live): re-insert after delete
  // resurrects the id, inserting an existing id replaces it — matching
  // the upsert/delete semantics of the SQLite and memory backends.
  std::vector<const Rec*> live;
  collect_live(recs, &live);

  std::vector<const Rec*> hits;
  for (const Rec* rp : live) {
    const Rec& r = *rp;
    if (r.time_us < start_us || r.time_us >= until_us) continue;
    if (event_name_count > 0 &&
        !in_set(r.str[1], r.len[1], event_names, event_name_count))
      continue;
    if (entity_type[0] && !str_eq(r.str[2], r.len[2], entity_type)) continue;
    if (entity_id[0] && !str_eq(r.str[3], r.len[3], entity_id)) continue;
    if (target_entity_type[0] &&
        !str_eq(r.str[4], r.len[4], target_entity_type))
      continue;
    if (target_entity_id[0] &&
        !str_eq(r.str[5], r.len[5], target_entity_id))
      continue;
    if (event_id[0] && !str_eq(r.str[0], r.len[0], event_id)) continue;
    hits.push_back(&r);
  }

  std::sort(hits.begin(), hits.end(), [&](const Rec* a, const Rec* b) {
    if (a->time_us != b->time_us)
      return reversed ? a->time_us > b->time_us : a->time_us < b->time_us;
    return reversed ? a->seq > b->seq : a->seq < b->seq;
  });
  if (limit >= 0 && static_cast<int64_t>(hits.size()) > limit)
    hits.resize(static_cast<size_t>(limit));

  const int64_t n = static_cast<int64_t>(hits.size());
  out->n = n;
  out->time_us =
      static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (n ? n : 1)));
  out->ctime_us =
      static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (n ? n : 1)));
  if (!out->time_us || !out->ctime_us) {
    pel_free_result(out);
    return -4;
  }
  for (int c = 0; c < kNumStr; ++c) {
    uint64_t total = 0;
    for (const Rec* r : hits) total += r->len[c];
    if (total > UINT32_MAX) {
      pel_free_result(out);
      return -3;
    }
    out->arena[c] = static_cast<char*>(std::malloc(total ? total : 1));
    out->off[c] =
        static_cast<uint32_t*>(std::malloc(sizeof(uint32_t) * (n + 1)));
    if (!out->arena[c] || !out->off[c]) {
      pel_free_result(out);
      return -4;
    }
    uint32_t pos = 0;
    for (int64_t k = 0; k < n; ++k) {
      out->off[c][k] = pos;
      std::memcpy(out->arena[c] + pos, hits[k]->str[c], hits[k]->len[c]);
      pos += hits[k]->len[c];
    }
    out->off[c][n] = pos;
  }
  for (int64_t k = 0; k < n; ++k) {
    out->time_us[k] = hits[k]->time_us;
    out->ctime_us[k] = hits[k]->ctime_us;
  }
  return 0;
}

int pel_scan(const char* path, const char* event_names,
             int event_name_count, const char* entity_type,
             const char* entity_id, const char* target_entity_type,
             const char* target_entity_id, const char* event_id,
             int64_t start_us, int64_t until_us, int reversed,
             int64_t limit, PelResult* out) {
  std::memset(out, 0, sizeof(*out));
  try {
    return pel_scan_impl(path, event_names, event_name_count, entity_type,
                         entity_id, target_entity_type, target_entity_id,
                         event_id, start_us, until_us, reversed, limit,
                         out);
  } catch (...) {  // bad_alloc from vector/string growth, most likely
    pel_free_result(out);
    return -4;
  }
}

void pel_free_result(PelResult* r) {
  std::free(r->time_us);
  std::free(r->ctime_us);
  for (int c = 0; c < kNumStr; ++c) {
    std::free(r->arena[c]);
    std::free(r->off[c]);
  }
  std::memset(r, 0, sizeof(*r));
}

// Count live (non-tombstoned) records; -1 io error, -2 corrupt, -4 oom.
int64_t pel_count(const char* path) {
  try {
    std::vector<char> buf;
    if (!read_file(path, &buf)) return -1;
    std::vector<Rec> recs;
    size_t valid_end;
    if (!parse_records(buf, &recs, &valid_end)) return -2;
    std::vector<const Rec*> live;
    collect_live(recs, &live);
    return static_cast<int64_t>(live.size());
  } catch (...) {
    return -4;
  }
}

// Truncates a torn tail (partial record left by a crash mid-append) so
// later appends don't land after unreachable bytes, and upgrades v1 files
// to v2 (atomic rewrite adding per-record crcs) — appends are always
// v2-framed, so a v1 file must be converted before its first append.
// Called by the Python wrapper once per file before its first append in a
// process. Returns the number of torn-tail bytes dropped (0 = clean),
// -1 io error, -2 corrupt file, -4 oom.
int64_t pel_repair(const char* path) {
  try {
    std::vector<char> buf;
    if (!read_file(path, &buf)) return -1;
    if (buf.empty()) return 0;
    bool v1 = buf.size() >= 8 && std::memcmp(buf.data(), kMagicV1, 8) == 0;
    std::vector<Rec> recs;
    size_t valid_end;
    int version;
    if (!parse_records(buf, v1 ? &recs : nullptr, &valid_end, &version))
      return -2;
    int64_t dropped = static_cast<int64_t>(buf.size() - valid_end);
    if (version == 1) {
      // keep EVERY parsed record (tombstones and shadowed writes too):
      // repair restores framing invariants, compaction is a policy call
      std::vector<const Rec*> all;
      all.reserve(recs.size());
      for (const Rec& r : recs) all.push_back(&r);
      std::string tmp = std::string(path) + ".upgrade";
      FILE* f = std::fopen(tmp.c_str(), "wb");
      if (!f) return -1;
      bool ok = write_records_v2(f, all);
      ok = ok && std::fflush(f) == 0 && fsync(fileno(f)) == 0;
      ok = (std::fclose(f) == 0) && ok;
      if (!ok || std::rename(tmp.c_str(), path) != 0) {
        std::remove(tmp.c_str());
        return -1;
      }
      return dropped;
    }
    if (valid_end == buf.size()) return 0;
    FILE* f = std::fopen(path, "rb+");
    if (!f) return -1;
    int rc = std::fflush(f) == 0 &&
                     ftruncate(fileno(f), static_cast<off_t>(valid_end)) == 0
                 ? 0
                 : -1;
    std::fclose(f);
    return rc == 0 ? dropped : -1;
  } catch (...) {
    return -4;
  }
}

// Rewrites the log keeping only live records (dropping tombstones and
// records shadowed by a newer write of the same event id), preserving
// order. Atomic: writes <path>.compact then renames over the original.
// Returns bytes reclaimed (0 = nothing to do), -1 io error, -2 corrupt,
// -4 oom.
int64_t pel_compact(const char* path) {
  try {
    std::vector<char> buf;
    if (!read_file(path, &buf)) return -1;
    if (buf.empty()) return 0;
    std::vector<Rec> recs;
    size_t valid_end;
    if (!parse_records(buf, &recs, &valid_end)) return -2;

    std::vector<const Rec*> live;
    collect_live(recs, &live);
    int64_t live_bytes = 8;  // magic
    for (const Rec* r : live) {
      uint64_t payload = kHeaderFixed;
      for (int c = 0; c < kNumStr; ++c) payload += r->len[c];
      live_bytes += 4 + static_cast<int64_t>(payload) + 4;  // len + crc
    }
    int64_t reclaimed = static_cast<int64_t>(buf.size()) - live_bytes;
    if (reclaimed <= 0) return 0;

    std::string tmp = std::string(path) + ".compact";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    bool ok = write_records_v2(f, live);
    // fsync BEFORE the rename: fflush only reaches the page cache, and a
    // rename-then-crash could otherwise leave a truncated file where the
    // intact original used to be (append-path fflush bounds loss to one
    // record; a rewrite must not risk the whole log)
    ok = ok && std::fflush(f) == 0 && fsync(fileno(f)) == 0;
    ok = (std::fclose(f) == 0) && ok;
    if (!ok || std::rename(tmp.c_str(), path) != 0) {
      std::remove(tmp.c_str());
      return -1;
    }
    return reclaimed;
  } catch (...) {
    return -4;
  }
}

}  // extern "C"
