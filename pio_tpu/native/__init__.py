"""Native (C++) runtime components, built on first use.

The reference's native substrate is the JVM + Spark (no C++/CUDA anywhere —
SURVEY.md §2); this package holds the rebuild's own native pieces:

- ``event_log.cpp`` — append-only binary event log with C++ filtered scan
  (pio_tpu/storage/eventlog.py wraps it as a storage backend).
- ``als_pack.cpp`` — parallel COO→blocked-CSR packer feeding the ALS
  trainer's coalesced device transfer (pio_tpu/models/als.py).

Build model: no wheels, no pybind11 — ``g++ -O3 -march=native`` at first
import, cached under ``$PIO_TPU_HOME/native/<src+flags sha>-<isa>.so`` so
rebuilds happen when the source, flags, or host ISA change (a
native-codegen binary never loads on a CPU missing its instructions).
ctypes loads the result. Environments
without a toolchain get :class:`NativeUnavailable` and callers fall back to
pure-Python backends.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

from pio_tpu.utils import knobs

log = logging.getLogger("pio_tpu.native")

_lock = threading.Lock()
_cache: dict = {}


class NativeUnavailable(RuntimeError):
    """No compiler / compile failed — use a pure-Python backend instead."""


def _build_dir() -> str:
    home = knobs.knob_str("PIO_TPU_HOME") or os.path.expanduser("~/.pio_tpu")
    d = os.path.join(home, "native")
    os.makedirs(d, exist_ok=True)
    return d


_FLAGS = ["-O3", "-march=native", "-shared", "-fPIC", "-std=c++17"]


def _host_isa_tag() -> str:
    """Short tag of this host's ISA feature set — part of the .so cache
    key, so a ``-march=native`` binary built on one CPU (shared home,
    baked image) is never loaded on a CPU missing its instructions
    (SIGILL), it just rebuilds."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha256(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    # no /proc/cpuinfo (macOS, sandbox): fall back to a platform string —
    # coarser than the feature set, but never a shared constant that
    # would let one host's -march=native binary load on another
    import platform

    return hashlib.sha256(
        f"{platform.system()}-{platform.machine()}-"
        f"{platform.processor()}".encode()
    ).hexdigest()[:8]


def build_library(name: str) -> str:
    """Compile ``<name>.cpp`` (beside this file) → cached .so path.
    Cache key = source hash + compile flags + host ISA tag."""
    src = os.path.join(os.path.dirname(__file__), f"{name}.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(
            f.read() + " ".join(_FLAGS).encode()
        ).hexdigest()[:16]
    out = os.path.join(
        _build_dir(), f"{name}-{digest}-{_host_isa_tag()}.so"
    )
    if os.path.exists(out):
        return out
    tmp = f"{out}.{os.getpid()}.tmp"  # per-process: concurrent first builds
    # -O3 + -march=native: the packers and the host scorer are SIMD-bound
    # inner loops; the ISA tag above keeps native codegen host-correct
    cmd = ["g++", *_FLAGS, "-o", tmp, src]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeUnavailable(f"cannot run g++: {e}") from e
    if proc.returncode != 0:
        raise NativeUnavailable(
            f"g++ failed for {src}:\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)
    log.info("built native library %s", out)
    return out


_NUM_STR = 9  # string columns in a PelResult (see event_log.cpp)


class PelResult(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int64),
        ("time_us", ctypes.POINTER(ctypes.c_int64)),
        ("ctime_us", ctypes.POINTER(ctypes.c_int64)),
        # POINTER(c_char), not c_char_p: arenas are length-delimited binary
        # (c_char_p would truncate at the first NUL on conversion)
        ("arena", ctypes.POINTER(ctypes.c_char) * _NUM_STR),
        ("off", ctypes.POINTER(ctypes.c_uint32) * _NUM_STR),
    ]


def event_log_lib():
    """Load (building if needed) the event-log library; cached."""
    with _lock:
        if "event_log" in _cache:
            return _cache["event_log"]
        # first-use compile fills the cache: serializing the build
        # under _lock is the point (one compiler run per library)
        # pio: disable=lock-blocking-call
        lib = ctypes.CDLL(build_library("event_log"))
        lib.pel_append.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int,  # do_sync (durability knob)
        ]
        lib.pel_append.restype = ctypes.c_int
        lib.pel_scan.argtypes = [
            ctypes.c_char_p,  # path
            ctypes.c_char_p, ctypes.c_int,  # event_names set, count
            ctypes.c_char_p, ctypes.c_char_p,  # entity_type, entity_id
            ctypes.c_char_p, ctypes.c_char_p,  # target type/id
            ctypes.c_char_p,  # event_id
            ctypes.c_int64, ctypes.c_int64,  # start, until (us)
            ctypes.c_int, ctypes.c_int64,  # reversed, limit
            ctypes.POINTER(PelResult),
        ]
        lib.pel_scan.restype = ctypes.c_int
        lib.pel_free_result.argtypes = [ctypes.POINTER(PelResult)]
        lib.pel_free_result.restype = None
        lib.pel_count.argtypes = [ctypes.c_char_p]
        lib.pel_count.restype = ctypes.c_int64
        lib.pel_repair.argtypes = [ctypes.c_char_p]
        lib.pel_repair.restype = ctypes.c_int64
        lib.pel_compact.argtypes = [ctypes.c_char_p]
        lib.pel_compact.restype = ctypes.c_int64
        _cache["event_log"] = lib
        return lib


def als_pack_lib():
    """Load (building if needed) the ALS packer library; cached."""
    with _lock:
        if "als_pack" in _cache:
            return _cache["als_pack"]
        # first-use compile fills the cache: serializing the build
        # under _lock is the point (one compiler run per library)
        # pio: disable=lock-blocking-call
        lib = ctypes.CDLL(build_library("als_pack"))
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.als_pack_count.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, i64p
        ]
        lib.als_pack_count.restype = ctypes.c_int64
        lib.als_pack_fill.argtypes = [
            i32p, i32p, f32p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, i64p, ctypes.c_int64, i32p, i32p, f32p,
        ]
        lib.als_pack_fill.restype = ctypes.c_int
        lib.als_sort_by_entity.argtypes = [
            i32p, i32p, f32p, ctypes.c_int64, ctypes.c_int32, i64p,
            i32p, f32p,
        ]
        lib.als_sort_by_entity.restype = ctypes.c_int
        lib.als_sort_within_entity.argtypes = [
            i32p, f32p, ctypes.c_int32, i64p,
        ]
        lib.als_sort_within_entity.restype = ctypes.c_int
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.als_delta_count.argtypes = [i32p, i64p, ctypes.c_int32]
        lib.als_delta_count.restype = ctypes.c_int64
        lib.als_delta_fill.argtypes = [
            i32p, i64p, ctypes.c_int32, ctypes.c_int64,
            u8p, u8p, i32p, u8p,
        ]
        lib.als_delta_fill.restype = ctypes.c_int
        lib.als_rating_codes.argtypes = [f32p, ctypes.c_int64, u8p]
        lib.als_rating_codes.restype = ctypes.c_int64
        _cache["als_pack"] = lib
        return lib


def topn_host_lib():
    """Load (building if needed) the host top-N scorer library; cached."""
    with _lock:
        if "topn_host" in _cache:
            return _cache["topn_host"]
        # first-use compile fills the cache: serializing the build
        # under _lock is the point (one compiler run per library)
        # pio: disable=lock-blocking-call
        lib = ctypes.CDLL(build_library("topn_host"))
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.topn_host_f32.argtypes = [
            f32p, f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            i32p, ctypes.c_int64, ctypes.c_int32, i64p, f32p,
        ]
        lib.topn_host_f32.restype = ctypes.c_int
        _cache["topn_host"] = lib
        return lib
