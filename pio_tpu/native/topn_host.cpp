// Native host-mirror top-N scorer — the serving hot loop when the
// accelerator path doesn't pay (single queries over a slow link, or
// non-device pool workers; see pio_tpu/ops/topn.py).
//
// The numpy path is two passes per query: a [1, K] @ [K, N] BLAS matmul
// materializing all N scores, then argpartition+argsort over them. This
// kernel works from a TRANSPOSED [K, N] table in L1-sized column blocks:
// scores accumulate with stride-1 FMA over each block (auto-vectorized
// at -O3 -march=native), then a guarded scan updates a top-n min-heap —
// the N-float score array never exists and the selection pass touches
// each block while it is still cache-hot.
//
// Results are sorted by (-score, index): deterministic under ties, which
// the numpy argpartition path never guaranteed.

#include <algorithm>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

constexpr int32_t BLOCK = 4096;  // 16 KB of f32 scores — L1-resident

struct Entry {
  float score;
  int32_t idx;
};

// comparator for a MIN-heap on score (std heap primitives build a
// max-heap by "less"; inverting the score compare puts the smallest
// score at the root). Ties: the larger index sits nearer the root, so
// the smaller index survives eviction — matching the (-score, idx)
// output order.
inline bool heap_less(const Entry& a, const Entry& b) {
  if (a.score != b.score) return a.score > b.score;
  return a.idx < b.idx;
}

}  // namespace

extern "C" {

// rows: [n_rows, K] query-side factors (row-major); cols_t: [K, n_cols]
// TRANSPOSED table (row-major). codes: [B] row indices. Writes
// out_idx/out_val [B, topn] sorted by (-score, idx); topn <= n_cols.
// Returns 0, or -1 on a code outside [0, n_rows).
int topn_host_f32(const float* rows, const float* cols_t, int32_t n_rows,
                  int32_t n_cols, int32_t k_rank, const int32_t* codes,
                  int64_t b, int32_t topn, int64_t* out_idx,
                  float* out_val) {
  std::vector<Entry> heap(topn);
  float blk[BLOCK];
  for (int64_t q = 0; q < b; ++q) {
    int32_t code = codes[q];
    if (code < 0 || code >= n_rows) return -1;
    const float* qv = rows + static_cast<int64_t>(code) * k_rank;
    int32_t filled = 0;
    float thresh = 0.0f;  // valid once filled == topn
    for (int32_t j0 = 0; j0 < n_cols; j0 += BLOCK) {
      const int32_t w = std::min(BLOCK, n_cols - j0);
      if (k_rank == 0) {  // degenerate rank: every dot product is 0
        for (int32_t j = 0; j < w; ++j) blk[j] = 0.0f;
      } else {
        const float* c0 = cols_t + j0;
        const float q0 = qv[0];
        for (int32_t j = 0; j < w; ++j) blk[j] = q0 * c0[j];
      }
      for (int32_t k = 1; k < k_rank; ++k) {
        const float* ck = cols_t + static_cast<int64_t>(k) * n_cols + j0;
        const float qk = qv[k];
        for (int32_t j = 0; j < w; ++j) blk[j] += qk * ck[j];
      }
      // selection while the block is cache-hot; the threshold test is
      // almost always false, so the heap machinery rarely runs
      for (int32_t j = 0; j < w; ++j) {
        float s = blk[j];
        // NaN (diverged factors / corrupt model) would break the strict
        // weak ordering std::sort and the heap require — UB that can
        // crash the server. Both host paths map NaN to -inf: it ranks
        // tied-last and SURFACES as -inf (pio_tpu/ops/topn.py keeps the
        // numpy path in exact agreement).
        if (!(s == s)) s = -std::numeric_limits<float>::infinity();
        if (filled < topn) {
          heap[filled++] = {s, j0 + j};
          if (filled == topn) {
            std::make_heap(heap.begin(), heap.end(), heap_less);
            thresh = heap[0].score;
          }
        } else if (s > thresh) {
          std::pop_heap(heap.begin(), heap.end(), heap_less);
          heap[topn - 1] = {s, j0 + j};
          std::push_heap(heap.begin(), heap.end(), heap_less);
          thresh = heap[0].score;
        }
      }
    }
    std::sort(heap.begin(), heap.begin() + filled, heap_less);
    for (int32_t r = 0; r < topn; ++r) {
      out_idx[q * topn + r] = r < filled ? heap[r].idx : 0;
      out_val[q * topn + r] = r < filled ? heap[r].score : 0.0f;
    }
  }
  return 0;
}

}  // extern "C"
