"""``python -m pio_tpu`` — the `pio` CLI equivalent."""

import sys

from pio_tpu.tools.cli import main

if __name__ == "__main__":
    sys.exit(main())
