"""Event import/export as JSON-lines files.

Rebuild of the reference's ``tools/.../tools/export/EventsToFile.scala`` and
``tools/.../tools/imprt/FileToEvents.scala`` (UNVERIFIED paths; see
SURVEY.md). Lines use the Event wire format (camelCase), so exports from the
reference's SDKs import unchanged.
"""

from __future__ import annotations

import json
from typing import Optional, Tuple

from pio_tpu.data.event import Event, EventValidationError
from pio_tpu.storage import Storage


def import_events(
    path: str, app_id: int, channel_id: Optional[int] = None,
    batch_size: int = 5000,
) -> Tuple[int, int]:
    """Returns (imported, failed). Bad lines are skipped, not fatal."""
    pevents = Storage.get_pevents()
    imported = failed = 0
    batch = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                batch.append(Event.from_api_dict(json.loads(line)))
            except (json.JSONDecodeError, EventValidationError):
                failed += 1
                continue
            if len(batch) >= batch_size:
                pevents.write(batch, app_id, channel_id)
                imported += len(batch)
                batch = []
    if batch:
        pevents.write(batch, app_id, channel_id)
        imported += len(batch)
    return imported, failed


def export_events(
    path: str, app_id: int, channel_id: Optional[int] = None
) -> int:
    events = Storage.get_pevents().find(app_id, channel_id=channel_id)
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e.to_api_dict()) + "\n")
    return len(events)
