"""`pio`-equivalent CLI (reference ``tools/.../console/Console.scala``,
UNVERIFIED path; see SURVEY.md).

Verbs: app, accesskey, train, eval, deploy, undeploy, batchpredict,
eventserver, import, export, status, version. Unlike the reference there is
no spark-submit process fork — train runs in-process on the local TPU/mesh.

Usage: ``python -m pio_tpu <verb> ...``
"""

from __future__ import annotations

import argparse
import importlib
import json
import logging
import os
import sys
import urllib.request
from typing import Optional

import pio_tpu

from pio_tpu.utils import knobs


def _out(s: str = ""):
    print(s)


def _err(s: str) -> int:
    print(f"[ERROR] {s}", file=sys.stderr)
    return 1


def _storage():
    from pio_tpu.storage import Storage

    return Storage


def _resolve_app(name: str):
    app = _storage().get_meta_data_apps().get_by_name(name)
    if app is None:
        raise SystemExit(_err(f"app {name!r} not found"))
    return app


def _channel_id(app_id: int, channel: Optional[str]):
    from pio_tpu.data.store import resolve_channel

    try:
        return resolve_channel(app_id, channel)
    except ValueError as e:
        raise SystemExit(_err(str(e)))


# ----------------------------------------------------------------- app verbs
def cmd_app_new(args) -> int:
    from pio_tpu.storage import AccessKey, App

    apps = _storage().get_meta_data_apps()
    app_id = apps.insert(App(0, args.name, args.description))
    if app_id is None:
        return _err(f"app {args.name!r} already exists")
    key = _storage().get_meta_data_access_keys().insert(AccessKey("", app_id))
    _out(f"App created: id={app_id} name={args.name}")
    _out(f"Access key: {key}")
    return 0


def cmd_app_list(args) -> int:
    keys = _storage().get_meta_data_access_keys()
    for app in _storage().get_meta_data_apps().get_all():
        ks = [k.key for k in keys.get_by_app_id(app.id)]
        _out(f"id={app.id} name={app.name} accessKeys={','.join(ks) or '-'}")
    return 0


def cmd_app_delete(args) -> int:
    app = _resolve_app(args.name)
    store = _storage()
    for k in store.get_meta_data_access_keys().get_by_app_id(app.id):
        store.get_meta_data_access_keys().delete(k.key)
    for c in store.get_meta_data_channels().get_by_app_id(app.id):
        store.get_meta_data_channels().delete(c.id)
        _delete_events(app.id, c.id)
    _delete_events(app.id, None)
    store.get_meta_data_apps().delete(app.id)
    _out(f"App {args.name!r} deleted")
    return 0


def _delete_events(app_id, channel_id):
    from pio_tpu.storage import StorageConfigError

    store = _storage()
    try:
        store.get_levents().remove(app_id, channel_id)
    except StorageConfigError:
        # bulk-only backend (parquet) has no LEvents; delete via PEvents
        pe = store.get_pevents()
        ids = [e.event_id for e in pe.find(app_id, channel_id=channel_id)]
        if ids:
            pe.delete(ids, app_id, channel_id)


def cmd_app_data_delete(args) -> int:
    app = _resolve_app(args.name)
    _delete_events(app.id, _channel_id(app.id, args.channel))
    _out(f"Event data deleted for app {args.name!r}"
         + (f" channel {args.channel!r}" if args.channel else ""))
    return 0


def cmd_app_compact(args) -> int:
    """Reclaim space in the event store: eventlog drops tombstones and
    shadowed upserts, parquet merges shards. No-op for backends without a
    compact operation."""
    from pio_tpu.storage import StorageConfigError

    app = _resolve_app(args.name)
    channel_id = _channel_id(app.id, args.channel)
    store = _storage()
    try:
        backend = store.get_levents()
    except StorageConfigError:
        # bulk-only backend (parquet) has no LEvents side
        backend = store.get_pevents()
    if not hasattr(backend, "compact"):
        _out(f"backend {type(backend).__name__} does not need compaction")
        return 0
    n = backend.compact(app.id, channel_id)
    _out(
        f"compacted app {args.name!r}"
        + (f": reclaimed {n} bytes" if n is not None else "")
    )
    return 0


def cmd_channel_new(args) -> int:
    from pio_tpu.storage import Channel

    app = _resolve_app(args.app)
    chans = _storage().get_meta_data_channels().get_by_app_id(app.id)
    if any(c.name == args.channel for c in chans):
        return _err(
            f"channel {args.channel!r} already exists for app {args.app!r}"
        )
    cid = _storage().get_meta_data_channels().insert(
        Channel(0, args.channel, app.id)
    )
    if cid is None:
        return _err(
            f"cannot create channel {args.channel!r} ({Channel.NAME_CONSTRAINT})"
        )
    _out(f"Channel created: id={cid} name={args.channel} app={args.app}")
    return 0


def cmd_channel_delete(args) -> int:
    app = _resolve_app(args.app)
    cid = _channel_id(app.id, args.channel)
    _delete_events(app.id, cid)
    _storage().get_meta_data_channels().delete(cid)
    _out(f"Channel {args.channel!r} deleted")
    return 0


# ----------------------------------------------------------- accesskey verbs
def cmd_accesskey_new(args) -> int:
    from pio_tpu.storage import AccessKey

    app = _resolve_app(args.app)
    events = tuple(e for e in (args.events or "").split(",") if e)
    key = _storage().get_meta_data_access_keys().insert(
        AccessKey("", app.id, events)
    )
    _out(f"Access key: {key}")
    return 0


def cmd_accesskey_list(args) -> int:
    keys = _storage().get_meta_data_access_keys()
    items = (
        keys.get_by_app_id(_resolve_app(args.app).id) if args.app else keys.get_all()
    )
    for k in items:
        _out(f"key={k.key} appId={k.app_id} events={','.join(k.events) or '(all)'}")
    return 0


def cmd_accesskey_delete(args) -> int:
    if not _storage().get_meta_data_access_keys().delete(args.key):
        return _err("key not found")
    _out("Access key deleted")
    return 0


# -------------------------------------------------------------- train / eval
def _load_variant(path: str):
    from pio_tpu.workflow import load_variant

    return load_variant(path)


def cmd_train(args) -> int:
    from pio_tpu.parallel.context import ComputeContext
    from pio_tpu.workflow import WorkflowParams, build_engine, run_train

    if args.checkpoint_dir and not args.checkpoint_every:
        raise SystemExit(_err(
            "--checkpoint-dir has no effect without --checkpoint-every N "
            "(nothing would be snapshotted)"
        ))
    faults = getattr(args, "faults", None) or None
    if faults:
        from pio_tpu import faults as _faults

        _faults.parse_faults(faults)
        os.environ["PIO_TPU_FAULTS"] = faults
        _faults.install(faults)
    variant = _load_variant(args.engine_json)
    engine, ep = build_engine(variant)
    wp = WorkflowParams(
        batch=args.batch,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
        seed=args.seed,
        profile_dir=args.profile_dir,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
    )
    ctx = ComputeContext.create(seed=args.seed)
    status_port = args.status_port
    if status_port is None:
        status_port = knobs.knob_int("PIO_TPU_TRAIN_STATUS_PORT")
    status_server = None
    if status_port >= 0:
        from pio_tpu.server.fleetd import create_train_status_server

        status_server = create_train_status_server(port=status_port)
        status_server.start()
        _out(f"Training status sidecar on 127.0.0.1:{status_server.port} "
             "(/train.json /metrics /logs.json)")
    try:
        instance_id = run_train(engine, ep, variant, wp, ctx=ctx)
    finally:
        if status_server is not None:
            status_server.stop()
    _out(f"Training completed: engine instance {instance_id}")
    return 0


def cmd_runs(args) -> int:
    """Inspect the run registry (ISSUE 16): ``$PIO_TPU_HOME/runs/
    <engine-id>.jsonl``, one row per ``run_train``. List by default;
    ``--diff`` compares the last two COMPLETED runs with the bench
    ledger's direction-aware regression logic (exit 1 on regression)."""
    from pio_tpu.obs import trainwatch

    engine_id = args.engine_id
    if not engine_id:
        variant = _load_variant(args.engine_json)
        engine_id = variant.engine_id
    rows = trainwatch.read_runs(engine_id)
    if not rows:
        return _err(
            f"no recorded runs for engine {engine_id!r} "
            f"(ledger: {trainwatch.runs_path(engine_id)})"
        )
    if args.n and not args.diff:
        rows = rows[-args.n:]
    if args.json:
        _out(json.dumps(rows, indent=2, sort_keys=True))
        return 0
    if args.diff:
        done = [r for r in rows if r.get("status") == "COMPLETED"]
        if len(done) < 2:
            return _err(
                f"--diff needs two COMPLETED runs for {engine_id!r} "
                f"(have {len(done)})"
            )
        threshold = (
            args.threshold if args.threshold is not None
            else trainwatch.DEFAULT_RUN_THRESHOLD
        )
        lines, regressed = trainwatch.run_delta_table(
            done[-2], done[-1], threshold=threshold,
        )
        for line in lines:
            _out(line)
        if regressed:
            _err("run regression in: " + ", ".join(regressed))
            return 1
        return 0
    _out(f"{'run_id':<36} {'timestamp':<26} {'status':<10} "
         f"{'train_s':>9} {'algo':<12} {'loss':>10}")
    for r in rows:
        loss = r.get("final_loss")
        _out(f"{str(r.get('run_id') or '?'):<36} "
             f"{str(r.get('timestamp') or '?'):<26} "
             f"{str(r.get('status') or '?'):<10} "
             f"{r.get('train_seconds', 0):>9} "
             f"{str((r.get('step_summary') or {}).get('algo') or '-'):<12} "
             f"{loss if loss is not None else '-':>10}")
    return 0


def _import_attr(spec: str, call: bool = True):
    """Resolve ``module:attr``; with ``call`` (the eval-verb convention),
    zero-arg callables are invoked to produce the object."""
    mod_name, _, attr = spec.partition(":")
    mod = importlib.import_module(mod_name)
    if not attr:
        return mod
    obj = getattr(mod, attr)
    return obj() if call and callable(obj) else obj


def cmd_eval(args) -> int:
    from pio_tpu.parallel.context import ComputeContext
    from pio_tpu.workflow import run_evaluation

    evaluation = _import_attr(args.evaluation)
    generator = (
        _import_attr(args.engine_params_generator)
        if args.engine_params_generator
        else None
    )
    if generator is None:
        generator = getattr(evaluation, "engine_params_generator", None)
    if generator is None:
        return _err(
            "no EngineParamsGenerator: pass --engine-params-generator or set "
            ".engine_params_generator on the Evaluation"
        )
    result = run_evaluation(
        evaluation,
        generator,
        ctx=ComputeContext.create(),
        evaluation_class=args.evaluation,
        generator_class=args.engine_params_generator or "",
    )
    _out(f"Best params (score {result.best_score}):")
    _out(result.to_json())
    return 0


# ------------------------------------------------------------------- servers
def cmd_eventserver(args) -> int:
    import os

    from pio_tpu.server import create_event_server

    faults = getattr(args, "faults", None) or None
    if faults:
        from pio_tpu import faults as _faults

        _faults.parse_faults(faults)
        os.environ["PIO_TPU_FAULTS"] = faults
        _faults.install(faults)
    server = create_event_server(host=args.ip, port=args.port)
    _out(f"Event Server listening on {args.ip}:{server.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    return 0


def cmd_blobserver(args) -> int:
    """Run the blob daemon — the remote Models endpoint (HDFS/S3 slot).
    Point MODELDATA at it: PIO_STORAGE_SOURCES_<N>_TYPE=blob,
    PIO_STORAGE_SOURCES_<N>_PATH=http://host:port[?accessKey=…]."""
    from pio_tpu.server.blob_server import create_blob_server

    server = create_blob_server(
        args.root, host=args.ip, port=args.port, access_key=args.access_key
    )
    _out(f"Blob server serving {args.root} on {args.ip}:{server.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    return 0


def cmd_dashboard(args) -> int:
    from pio_tpu.server import create_dashboard

    server = create_dashboard(
        host=args.ip, port=args.port, query_url=args.query_url,
        fleet_targets=args.fleet_targets, train_url=args.train_url,
    )
    _out(f"Dashboard listening on {args.ip}:{server.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    return 0


def cmd_fleet(args) -> int:
    """Run the fleet telemetry aggregator (ISSUE 11): scrape every
    ``--targets`` member, serve the federated ``/metrics`` and the
    ``/fleet.json`` cluster status the router steers by."""
    import os

    from pio_tpu.obs.fleet import TARGETS_ENV
    from pio_tpu.server.fleetd import create_fleet_server

    targets = args.targets or os.environ.get(TARGETS_ENV, "")
    if not targets.strip():
        _err(
            "no fleet targets: pass --targets host:port,... or set "
            f"{TARGETS_ENV}"
        )
        return 1
    server = create_fleet_server(
        targets, host=args.ip, port=args.port, interval_s=args.interval,
    )
    server.service.agg.start()
    members = ", ".join(m.name for m in server.service.agg.members())
    _out(f"Fleet aggregator listening on {args.ip}:{server.port} "
         f"(members: {members})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    finally:
        server.service.agg.stop()
    return 0


def cmd_route(args) -> int:
    """Run the serving-router daemon (ISSUE 18), or — with ``--deploy``
    — push a manifest-verified rollout through a running one."""
    import os

    from pio_tpu.obs.fleet import TARGETS_ENV

    if args.deploy:
        import json as _json
        import urllib.request

        body = _json.dumps({"engineInstanceId": args.deploy}).encode()
        headers = {"Content-Type": "application/json; charset=utf-8"}
        if args.admin_key:
            headers["Authorization"] = f"Bearer {args.admin_key}"
        req = urllib.request.Request(
            args.url.rstrip("/") + "/deploy",
            data=body, headers=headers, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=args.timeout) as resp:
                report = _json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            _err(f"deploy failed: HTTP {e.code}: "
                 f"{e.read().decode('utf-8', 'replace')[:500]}")
            return 1
        except Exception as e:
            _err(f"deploy failed: cannot reach router at {args.url}: {e}")
            return 1
        for row in report.get("members", []):
            _out(f"  {row['member']}: {row['outcome']}")
        ok = report.get("verified") == len(report.get("members", []))
        _out(
            f"instance {report.get('engineInstanceId')}: "
            f"{report.get('verified')}/{len(report.get('members', []))} "
            f"member(s) verified"
        )
        return 0 if ok else 1

    from pio_tpu.obs.fleet import parse_targets
    from pio_tpu.server.routerd import create_router_server

    targets = args.targets or os.environ.get(TARGETS_ENV, "")
    if not targets.strip():
        _err(
            "no serving members: pass --targets host:port,... or set "
            f"{TARGETS_ENV}"
        )
        return 1
    server = create_router_server(
        parse_targets(targets),
        host=args.ip,
        port=args.port,
        partitions=args.partitions,
        interval_s=args.interval,
        admin_key=args.admin_key,
        timeout_s=args.timeout,
    )
    server.service.start()
    members = ", ".join(m.name for m in server.service.agg.members())
    _out(f"Serving router listening on {args.ip}:{server.port} "
         f"(members: {members})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    finally:
        server.service.stop()
    return 0


def cmd_rollout(args) -> int:
    """Drive the progressive-delivery controller on a running routerd
    (ISSUE 19): start a shadow->canary->promote rollout, abort one, or
    print the live stage + decision trail."""
    import json as _json
    import urllib.error
    import urllib.request

    base = args.url.rstrip("/")
    headers = {"Content-Type": "application/json; charset=utf-8"}
    if args.admin_key:
        headers["Authorization"] = f"Bearer {args.admin_key}"

    def call(method: str, path: str, body: Optional[dict] = None):
        data = _json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            base + path, data=data, headers=headers, method=method
        )
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            return _json.loads(resp.read().decode("utf-8"))

    try:
        if args.start:
            body = {
                "engineInstanceId": args.start,
                "targets": args.targets or "",
                "by": "pio rollout",
            }
            for key, val in (
                ("shadowRate", args.shadow_rate),
                ("shadowMinSamples", args.shadow_min_samples),
                ("shadowHoldSeconds", args.shadow_hold),
                ("canaryFraction", args.canary_fraction),
                ("canaryHoldSeconds", args.canary_hold),
                ("canaryMinRequests", args.canary_min_requests),
                ("judgeIntervalSeconds", args.judge_interval),
                ("judgeFastSeconds", args.judge_fast),
                ("judgeSlowSeconds", args.judge_slow),
                ("burnLimit", args.burn_limit),
                ("mismatchLimit", args.mismatch_limit),
                ("incumbentInstance", args.incumbent),
            ):
                if val is not None:
                    body[key] = val
            got = call("POST", "/rollout", body)
            ro = got.get("rollout") or {}
            _out(
                f"rollout #{ro.get('generation')} of {args.start} "
                f"started: stage {ro.get('stage')}"
            )
            return 0
        if args.abort:
            got = call("POST", "/rollout/abort", {})
            ro = got.get("rollout") or {}
            _out(f"rollout aborted: stage {ro.get('stage')}")
            return 0
        ro = call("GET", "/rollout.json")
    except urllib.error.HTTPError as e:
        _err(f"rollout request failed: HTTP {e.code}: "
             f"{e.read().decode('utf-8', 'replace')[:500]}")
        return 1
    except Exception as e:
        _err(f"cannot reach router at {base}: {e}")
        return 1

    _out(f"stage: {ro.get('stage')}")
    if ro.get("stage") == "idle":
        return 0
    _out(f"candidate: {ro.get('candidateInstance')}  "
         f"incumbent: {ro.get('incumbentInstance')}")
    shadow = ro.get("shadow") or {}
    _out(f"shadow: {shadow.get('samples', 0)} samples, "
         f"mismatch rate {shadow.get('mismatchRate', 0.0)}, "
         f"{shadow.get('dropped', 0)} dropped")
    canary = ro.get("canary") or {}
    _out(f"canary: {canary.get('requests', 0)} requests at fraction "
         f"{canary.get('fraction')}")
    judge = ro.get("judge") or {}
    _out(f"judge: {judge.get('ticks', 0)} ticks, last verdict "
         f"{judge.get('lastVerdict')}, burn {judge.get('burnRates')}")
    for entry in ro.get("trail") or []:
        window = f" [{entry['window']}]" if entry.get("window") else ""
        detail = f" — {entry['detail']}" if entry.get("detail") else ""
        _out(f"  {entry.get('from')} -> {entry.get('to')}: "
             f"{entry.get('signal')}{window}{detail}")
    return 0


def cmd_adminserver(args) -> int:
    from pio_tpu.server import create_admin_server

    server = create_admin_server(
        host=args.ip, port=args.port, admin_key=args.admin_key
    )
    _out(f"Admin API listening on {args.ip}:{server.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    return 0


def cmd_deploy(args) -> int:
    import os

    from pio_tpu.server import create_query_server

    if getattr(args, "profile_dir", ""):
        # serving profile hook (pio_tpu/obs/profile.py): capture a
        # jax.profiler trace of the first N device executions
        os.environ["PIO_TPU_PROFILE"] = args.profile_dir

    variant = _load_variant(args.engine_json)
    feedback_app_id = None
    if args.feedback_app:
        feedback_app_id = _resolve_app(args.feedback_app).id
    slos = list(getattr(args, "slo", None) or []) or None
    if slos:
        # fail fast on a typo'd spec, and export so pool worker
        # processes (spawn context) configure the same objectives
        from pio_tpu.obs.slo import parse_slo

        for spec in slos:
            parse_slo(spec)
        os.environ["PIO_TPU_SLO"] = ",".join(slos)
    qos = getattr(args, "qos", None) or None
    if qos:
        # same fail-fast + spawn-context export dance as --slo above
        from pio_tpu.qos import parse_qos

        parse_qos(qos)
        os.environ["PIO_TPU_QOS"] = qos
    faults = getattr(args, "faults", None) or None
    if faults:
        # fault injection: validate, export for pool workers (spawn
        # context re-arms from the env at import), arm this process
        from pio_tpu import faults as _faults

        _faults.parse_faults(faults)
        os.environ["PIO_TPU_FAULTS"] = faults
        _faults.install(faults)
    if getattr(args, "workers", 1) > 1:
        from pio_tpu.server.worker_pool import ServingPool

        pool = ServingPool(
            variant,
            host=args.ip,
            port=args.port,
            n_workers=args.workers,
            instance_id=args.engine_instance_id,
            feedback=bool(args.feedback_app),
            feedback_app_id=feedback_app_id,
            admin_key=args.admin_key,
            device_worker=args.device_worker,
            mesh_worker=getattr(args, "mesh_worker", False),
            slos=slos,
            qos=qos,
        )
        pool.start()
        # readiness-gated: wait_ready polls /readyz, so "listening" below
        # is only printed once a worker passes every readiness check
        pool.wait_ready()
        _out(
            f"Query Server pool ({args.workers} workers) listening on "
            f"{args.ip}:{pool.port}"
        )
        try:
            pool.wait()
        except KeyboardInterrupt:
            _out("shutting down pool")
            pool.stop()
        return 0
    server, service = create_query_server(
        variant,
        host=args.ip,
        port=args.port,
        instance_id=args.engine_instance_id,
        feedback=bool(args.feedback_app),
        feedback_app_id=feedback_app_id,
        admin_key=args.admin_key,
        slos=slos,
        qos=qos,
    )
    # reference parity: `pio undeploy` terminates the serving process
    service.attach_server(server)
    # readiness gate: the engine/models loaded in the constructor, but
    # only announce once every probe agrees (storage round trip included)
    ready, report = service.health.readiness()
    if not ready:
        _err(f"query server failed readiness: {report}")
        return 1
    _out(
        f"Query Server for instance {service.instance_id} "
        f"listening on {args.ip}:{server.port}"
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("shutting down")
    return 0


def cmd_undeploy(args) -> int:
    url = f"http://{args.ip}:{args.port}/undeploy"
    if args.admin_key:
        url += f"?accessKey={args.admin_key}"
    try:
        req = urllib.request.Request(url, data=b"{}", method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            _out(resp.read().decode())
        return 0
    except urllib.error.HTTPError as e:
        return _err(f"query server refused undeploy: {e.code} "
                    f"{e.read().decode(errors='replace')}")
    except OSError as e:
        return _err(f"cannot reach query server at {url}: {e}")


def cmd_batchpredict(args) -> int:
    from pio_tpu.workflow.batch_predict import run_batch_predict

    variant = _load_variant(args.engine_json)
    n = run_batch_predict(
        variant,
        args.input,
        args.output,
        instance_id=args.engine_instance_id,
    )
    _out(f"Batch predict done: {n} queries -> {args.output}")
    return 0


# ------------------------------------------------------------- import/export
def cmd_import(args) -> int:
    from pio_tpu.tools.data_io import import_events

    app = _resolve_app(args.app)
    imported, failed = import_events(
        args.input, app.id, _channel_id(app.id, args.channel)
    )
    _out(f"Imported {imported} events ({failed} failed)")
    return 0 if failed == 0 else 1


def cmd_export(args) -> int:
    from pio_tpu.tools.data_io import export_events

    app = _resolve_app(args.app)
    n = export_events(args.output, app.id, _channel_id(app.id, args.channel))
    _out(f"Exported {n} events -> {args.output}")
    return 0


# -------------------------------------------------------------------- status
def cmd_status(args) -> int:
    import jax

    from pio_tpu.storage import pio_home

    _out(f"pio-tpu {pio_tpu.__version__}")
    _out(f"home: {pio_home()}")
    try:
        devices = jax.devices()
        _out(f"devices: {[str(d) for d in devices]}")
    except Exception as e:
        _out(f"devices: unavailable ({e})")
    checks = _storage().verify_all_data_objects()
    ok = all(checks.values())
    for name, healthy in sorted(checks.items()):
        _out(f"  {'OK ' if healthy else 'FAIL'} {name}")
    _out("(sanity check " + ("passed)" if ok else "FAILED)"))
    try:
        insts = _storage().get_meta_data_engine_instances().get_all()
    except Exception:
        # status must degrade gracefully on the exact broken-backend
        # condition it reports (the FAIL lines above already said so)
        insts = []
    if insts:
        _out("recent engine instances:")
        for inst in sorted(
            insts, key=lambda i: i.start_time, reverse=True
        )[:5]:
            secs = inst.env.get("train_seconds", "")
            _out(
                f"  {inst.id[:12]}  {inst.status:<9} "
                f"{inst.engine_factory}"
                + (f"  ({secs}s)" if secs else "")
            )
    return 0 if ok else 1


def cmd_top(args) -> int:
    """Live device telemetry table (ISSUE 17): poll a ``/device.json``
    surface — the query server or a trainer status sidecar — and render
    per-device HBM plus the compile-site attribution, ``top``-style.
    ``--once`` prints a single snapshot and exits (scripting/tests)."""
    import time

    url = args.url.rstrip("/")
    mb = lambda v: (
        f"{v / 1048576.0:,.1f}" if isinstance(v, (int, float)) else "n/a"
    )

    def snapshot() -> Optional[str]:
        with urllib.request.urlopen(url + "/device.json", timeout=3.0) as r:
            data = json.loads(r.read().decode("utf-8"))
        budget = data.get("budgetBytes") or 0
        headroom = data.get("headroomBytes")
        lines = [
            f"pio-tpu devices  {url}/device.json",
            f"mode {data.get('mode', '?')}  gen {data.get('generation', 0)}"
            f"  samples {data.get('samples', 0)}"
            + (f"  budget {mb(budget)} MiB" if budget else "")
            + (f"  headroom {mb(headroom)} MiB"
               if headroom is not None else ""),
            "",
            f"{'dev':<5}{'in-use MiB':>12}{'peak MiB':>12}"
            f"{'limit MiB':>12}{'ledger MiB':>12}{'drift MiB':>12}  source",
        ]
        for d in data.get("devices") or []:
            lines.append(
                f"{d.get('device', '?'):<5}{mb(d.get('bytesInUse')):>12}"
                f"{mb(d.get('peakBytes')):>12}{mb(d.get('limitBytes')):>12}"
                f"{mb(d.get('ledgerBytes')):>12}{mb(d.get('driftBytes')):>12}"
                f"  {d.get('source', '-')}"
            )
        compiles = data.get("compiles") or {}
        lines += ["", f"compiles total {compiles.get('total', 0)}"]
        sites = compiles.get("sites") or {}
        if sites:
            lines.append(f"{'site':<18}{'count':>8}{'seconds':>10}")
            for site, row in sorted(sites.items()):
                lines.append(
                    f"{site:<18}{row.get('count', 0):>8}"
                    f"{row.get('seconds', 0.0):>10.3f}"
                )
        ledger = data.get("ledger") or {}
        placements = data.get("placements") or []
        lines += [
            "",
            f"placements {len(placements)}"
            f"  ledger {mb(ledger.get('totalBytes'))} MiB",
        ]
        return "\n".join(lines)

    remaining = 1 if args.once else args.iterations
    clear = not args.once and sys.stdout.isatty()
    try:
        while True:
            try:
                text = snapshot()
            except Exception as e:
                if args.once:
                    return _err(f"{url}/device.json unreachable: {e}")
                text = f"pio-tpu devices  {url}/device.json\nscrape failed: {e}"
            _out(("\x1b[2J\x1b[H" if clear else "") + text)
            if remaining:
                remaining -= 1
                if remaining == 0:
                    return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_version(args) -> int:
    _out(pio_tpu.__version__)
    return 0


def cmd_template_list(args) -> int:
    """List bundled engine factories (reference ``pio template`` browsed a
    remote gallery; bundled templates ship in-package here)."""
    import pio_tpu.templates  # noqa: F401  (registers the factories)
    from pio_tpu.controller.engine import engine_factory_names

    for name in engine_factory_names():
        _out(name)
    return 0


def cmd_upgrade(args) -> int:
    """Migrate configured SQLite storage to this build's schema
    (reference ``pio upgrade``). Opening a database applies pending
    migrations, so this verb just touches every configured store and
    reports the stamped schema version. ``--rebuild-search-index``
    additionally drops and refills every searchable store's FTS index —
    required after an out-of-band VACUUM (which may renumber the implicit
    rowids the index is keyed on)."""
    import sqlite3

    from pio_tpu.storage import StorageError
    from pio_tpu.storage.sqlite import SCHEMA_VERSION, SQLiteClient

    try:
        clients = _storage().sqlite_clients()
    except StorageError as e:  # schema newer than build, or misconfig
        return _err(str(e))
    except sqlite3.Error as e:  # failed migration SQL, locked db, ...
        return _err(f"migration failed: {e}")
    if not clients:
        _out("no SQLite stores configured; nothing to migrate")
        return 0
    seen_paths = set()
    rebuilt_paths = set()
    for label, client in clients.items():
        v = SQLiteClient.schema_version(client.conn())
        note = " (same file as above)" if client.path in seen_paths else ""
        seen_paths.add(client.path)
        _out(
            f"  {label}: {client.path} at schema v{v} "
            f"(current v{SCHEMA_VERSION}){note}"
        )
        rebuild = getattr(client, "rebuild_index", None)
        if (
            getattr(args, "rebuild_search_index", False)
            and callable(rebuild)
            and client.path not in rebuilt_paths
        ):
            try:
                rebuild()
            except sqlite3.Error as e:  # locked/corrupt db: clean error,
                return _err(f"index rebuild failed for {label}: {e}")
            rebuilt_paths.add(client.path)
            _out(f"  {label}: FTS index rebuilt")
    _out("storage schema up to date")
    return 0


def cmd_run(args) -> int:
    """Run a user entry point with the framework importable and storage
    configured (reference ``pio run <main class> -- args``): the target is
    ``module:function``, called with the passthrough argument list (or no
    arguments if it accepts none)."""
    import inspect
    import os

    # console-script installs don't put the invocation dir on sys.path the
    # way `python -m` does — the primary use case is a script in cwd
    if "" not in sys.path and os.getcwd() not in sys.path:
        sys.path.insert(0, os.getcwd())
    target = _import_attr(args.target, call=False)
    if not callable(target):
        return _err(f"{args.target!r} is not callable")
    argv = list(args.args)
    try:
        params = inspect.signature(target).parameters.values()
        takes_args = any(
            p.kind in (
                p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                p.VAR_POSITIONAL,
            )
            for p in params
        )
    except (TypeError, ValueError):  # some C-implemented callables
        takes_args = bool(argv)
    if argv and not takes_args:
        return _err(
            f"{args.target!r} accepts no positional arguments but "
            f"passthrough args were given: {argv}"
        )
    out = target(argv) if takes_args else target()
    return out if isinstance(out, int) else 0


def cmd_shell(args) -> int:
    """Interactive shell with the framework preloaded.

    Rebuild of ``bin/pio-shell`` + the pypio PySpark bridge (reference
    §2.4): where that dropped into a Spark shell with the PIO classpath
    and a py4j-backed ``PEventStore``, this drops into a Python REPL with
    the store facades, storage registry, and jax/jnp bound.
    """
    import code

    import jax
    import jax.numpy as jnp

    from pio_tpu.data.event import Event
    from pio_tpu.data.store import LEventStore, PEventStore

    ns = {
        "pio_tpu": pio_tpu,
        "Storage": _storage(),
        "PEventStore": PEventStore,
        "LEventStore": LEventStore,
        "Event": Event,
        "jax": jax,
        "jnp": jnp,
    }
    banner = (
        f"pio-tpu {pio_tpu.__version__} shell\n"
        "preloaded: Storage, PEventStore, LEventStore, Event, jax, jnp\n"
        'e.g.  PEventStore.find("myapp", event_names=["rate"])'
    )
    code.interact(banner=banner, local=ns, exitmsg="")
    return 0


def cmd_lint(args) -> int:
    """Project-native static analysis (see ``pio_tpu/analysis``).

    The reference system leaned on scalac + compile-time DSL checks to
    keep its multi-component server consistent; this is the Python
    equivalent, encoding the serving stack's concurrency and naming
    conventions as AST rules. Exit 0 = clean, 1 = findings.
    """
    from pio_tpu.analysis import all_rules, run_lint
    from pio_tpu.analysis.core import (
        collect_files,
        parse_module,
        render_json,
        render_text,
    )
    from pio_tpu.analysis.rules_convention import failpoint_inventory

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid:24s} [{rule.family}] {rule.description}")
        return 0

    paths = args.paths or ["pio_tpu", "tests"]
    if args.dump_failpoints or args.dump_callgraph or args.dump_effects \
            or args.dump_contracts:
        modules = []
        for path in collect_files(paths):
            parsed = parse_module(path)
            if hasattr(parsed, "tree"):   # skip unparsable files
                modules.append(parsed)
        if args.dump_failpoints:
            payload = {"failpoints": failpoint_inventory(modules)}
        elif args.dump_callgraph:
            from pio_tpu.analysis.effects import callgraph_inventory
            payload = {"callgraph": callgraph_inventory(modules)}
        elif args.dump_contracts:
            from pio_tpu.analysis.contracts import contracts_inventory
            from pio_tpu.analysis.core import LintContext
            payload = contracts_inventory(modules, LintContext())
        else:
            from pio_tpu.analysis.effects import (
                effects_inventory,
                frame_inventory,
            )
            payload = effects_inventory(modules)
            payload["frames"] = frame_inventory(modules)
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0

    only = None
    if args.changed:
        only = _changed_py_files(args.base)
        if only is not None and not only:
            print("pio lint: no changed python or docs files")
            return 0

    rule_ids = args.rules.split(",") if args.rules else None
    try:
        findings = run_lint(paths, rule_ids=rule_ids, only=only)
    except ValueError as exc:
        print(f"pio lint: {exc}", file=sys.stderr)
        return 2
    print(render_json(findings) if args.json else render_text(findings))
    return 1 if findings else 0


def _changed_py_files(base: str):
    """``git diff --name-only <base>`` filtered to .py plus docs/*.md,
    as absolute paths — or None (fall back to a full lint) when git is
    unavailable. Docs count: the knob table in docs/operations.md is a
    linted contract surface (knob-doc-drift), so a docs-only change
    must still re-lint contracts instead of early-exiting."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", base, "--"],
            capture_output=True, text=True, check=True,
        ).stdout
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
    except (OSError, subprocess.CalledProcessError) as exc:
        print(f"pio lint: --changed unavailable ({exc}); linting all",
              file=sys.stderr)
        return None
    return [
        os.path.join(top, line)
        for line in out.splitlines()
        if line.endswith(".py")
        or (line.endswith(".md") and line.startswith("docs/"))
    ]


# -------------------------------------------------------------------- parser
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio-tpu", description="TPU-native ML server CLI",
        epilog="global flags (-v/-q) go BEFORE the verb: pio-tpu -v train …",
    )
    vq = p.add_mutually_exclusive_group()
    vq.add_argument(
        "-v", "--verbose", action="count", default=0,
        help="debug logging (includes jax)",
    )
    vq.add_argument(
        "-q", "--quiet", action="store_true", help="warnings only"
    )
    sub = p.add_subparsers(dest="verb", required=True)

    app = sub.add_parser("app", help="manage apps").add_subparsers(
        dest="app_verb", required=True
    )
    a = app.add_parser("new")
    a.add_argument("name")
    a.add_argument("--description", default=None)
    a.set_defaults(fn=cmd_app_new)
    app.add_parser("list").set_defaults(fn=cmd_app_list)
    a = app.add_parser("delete")
    a.add_argument("name")
    a.set_defaults(fn=cmd_app_delete)
    a = app.add_parser("data-delete")
    a.add_argument("name")
    a.add_argument("--channel", default=None)
    a.set_defaults(fn=cmd_app_data_delete)
    a = app.add_parser("compact")
    a.add_argument("name")
    a.add_argument("--channel", default=None)
    a.set_defaults(fn=cmd_app_compact)
    a = app.add_parser("channel-new")
    a.add_argument("app")
    a.add_argument("channel")
    a.set_defaults(fn=cmd_channel_new)
    a = app.add_parser("channel-delete")
    a.add_argument("app")
    a.add_argument("channel")
    a.set_defaults(fn=cmd_channel_delete)

    ak = sub.add_parser("accesskey", help="manage access keys").add_subparsers(
        dest="ak_verb", required=True
    )
    a = ak.add_parser("new")
    a.add_argument("app")
    a.add_argument("--events", default="")
    a.set_defaults(fn=cmd_accesskey_new)
    a = ak.add_parser("list")
    a.add_argument("app", nargs="?")
    a.set_defaults(fn=cmd_accesskey_list)
    a = ak.add_parser("delete")
    a.add_argument("key")
    a.set_defaults(fn=cmd_accesskey_delete)

    a = sub.add_parser("train", help="run a training workflow")
    a.add_argument("--engine-json", default="engine.json")
    a.add_argument("--batch", default="")
    a.add_argument("--skip-sanity-check", action="store_true")
    a.add_argument("--stop-after-read", action="store_true")
    a.add_argument("--stop-after-prepare", action="store_true")
    a.add_argument("--seed", type=int, default=0)
    a.add_argument(
        "--profile-dir", default="",
        help="capture a jax.profiler trace of the train into this dir",
    )
    a.add_argument(
        "--checkpoint-every", type=int, default=0,
        help="snapshot training state every N steps; a preempted run "
             "restarted with the same engine.json resumes automatically",
    )
    a.add_argument(
        "--checkpoint-dir", default="",
        help="explicit snapshot dir (default: per-engine-config under "
             "$PIO_TPU_HOME)",
    )
    a.add_argument(
        "--status-port", type=int, default=None, metavar="PORT",
        help="loopback port for the live /train.json progress sidecar "
             "(default: PIO_TPU_TRAIN_STATUS_PORT or 0 = ephemeral, "
             "printed at start; negative disables)",
    )
    a.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="failpoint spec for fault drills, e.g. "
             "'stream.put=latency:0.02'; namespaces in "
             "`pio lint --dump-failpoints`",
    )
    a.set_defaults(fn=cmd_train)

    a = sub.add_parser(
        "runs", help="list / diff the training run registry"
    )
    a.add_argument("--engine-json", default="engine.json")
    a.add_argument(
        "--engine-id", default=None,
        help="ledger to read (default: the engine id of --engine-json)",
    )
    a.add_argument(
        "-n", type=int, default=0, metavar="N",
        help="show only the last N runs (0 = all)",
    )
    a.add_argument(
        "--diff", action="store_true",
        help="delta table for the last two COMPLETED runs; exits 1 when "
             "a field regresses past --threshold",
    )
    a.add_argument(
        "--threshold", type=float, default=None,
        help="fractional regression threshold for --diff (default 0.05)",
    )
    a.add_argument("--json", action="store_true",
                   help="raw ledger rows as JSON")
    a.set_defaults(fn=cmd_runs)

    a = sub.add_parser("eval", help="run an evaluation sweep")
    a.add_argument("evaluation", help="module:attr returning an Evaluation")
    a.add_argument(
        "engine_params_generator", nargs="?", default=None,
        help="module:attr returning an EngineParamsGenerator",
    )
    a.set_defaults(fn=cmd_eval)

    a = sub.add_parser("deploy", help="serve the trained engine over HTTP")
    a.add_argument("--engine-json", default="engine.json")
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=8000)
    a.add_argument("--engine-instance-id", default=None)
    a.add_argument(
        "--feedback-app", default=None,
        help="app name to log prediction feedback events into",
    )
    a.add_argument(
        "--admin-key", default=None,
        help="access key required by /reload and /undeploy; "
             "without one those routes are loopback-only",
    )
    a.add_argument(
        "--workers", type=int, default=1,
        help="serving processes sharing the port via SO_REUSEPORT "
             "(>1 multiplies host-path QPS on multi-core hosts; "
             "workers score on the host model mirror)",
    )
    a.add_argument(
        "--device-worker", action="store_true",
        help="with --workers>1: let worker 0 own the accelerator scorer "
             "(libtpu single-owner); others stay on the host mirror",
    )
    a.add_argument(
        "--mesh-worker", action="store_true",
        help="with --workers>1: let worker 0 own the WHOLE device mesh "
             "and serve mesh-sharded factor tables (PIO_TPU_MESH_SERVE; "
             "for models exceeding one chip's memory budget)",
    )
    a.add_argument(
        "--profile-dir", default="",
        help="capture a jax.profiler trace of the first N device "
             "executions into this dir (sets PIO_TPU_PROFILE; N from "
             "PIO_TPU_PROFILE_EXECUTIONS, default 8)",
    )
    a.add_argument(
        "--slo", action="append", default=[], metavar="SPEC",
        help="declare a serving SLO, repeatable: p99=50ms:99.9 (99.9%% "
             "of requests within 50 ms) or availability=99.9, optional "
             "/WINDOW suffix (e.g. /6h); evaluated live on /slo.json "
             "and exported as pio_tpu_slo_* gauges",
    )
    a.add_argument(
        "--qos", default=None, metavar="SPEC",
        help="admission control spec, e.g. "
             "'rps=500,queue=64,deadline=100ms' (keys: rps, burst, "
             "key_rps, key_burst, inflight, queue, deadline, cache, "
             "fail_rate, fail_window, probes, cooldown); excess load "
             "is shed with 429/503 + Retry-After, state on /qos.json; "
             "with --workers>1 the rps budget is pool-wide",
    )
    a.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="fault-injection spec (testing only), e.g. "
             "'eventlog.flush.*=error:0.1,storage.sqlite.commit="
             "latency:200ms,worker.serve=crash:once'; actions error, "
             "latency, torn-write, crash; state on /faults.json",
    )
    a.set_defaults(fn=cmd_deploy)

    a = sub.add_parser("undeploy", help="stop a running query server")
    a.add_argument("--ip", default="127.0.0.1")
    a.add_argument("--port", type=int, default=8000)
    a.add_argument(
        "--admin-key", default=None,
        help="admin access key if the server was deployed with one",
    )
    a.set_defaults(fn=cmd_undeploy)

    a = sub.add_parser("batchpredict", help="bulk offline scoring")
    a.add_argument("--engine-json", default="engine.json")
    a.add_argument("--input", required=True)
    a.add_argument("--output", required=True)
    a.add_argument("--engine-instance-id", default=None)
    a.set_defaults(fn=cmd_batchpredict)

    a = sub.add_parser("eventserver", help="run the event ingestion server")
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=7070)
    a.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="fault-injection spec (testing only), e.g. "
             "'storage.sqlite.commit=error:0.1'; state on /faults.json",
    )
    a.set_defaults(fn=cmd_eventserver)

    a = sub.add_parser(
        "blobserver", help="run the blob daemon (remote Models endpoint)"
    )
    a.add_argument("--root", required=True,
                   help="directory the daemon serves blobs from")
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=7088)
    a.add_argument("--access-key", default=None,
                   help="require this bearer key on every request")
    a.set_defaults(fn=cmd_blobserver)

    a = sub.add_parser("dashboard", help="run the evaluation dashboard")
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=9000)
    a.add_argument(
        "--query-url", default="http://127.0.0.1:8000",
        help="query server (or any pool worker) whose /metrics the "
             "/serving.html view scrapes",
    )
    a.add_argument(
        "--fleet-targets", default=None, metavar="HOST:PORT,...",
        help="enable the embedded /fleet.html panel scraping these "
             "members (default: PIO_TPU_FLEET_TARGETS)",
    )
    a.add_argument(
        "--train-url", default=None, metavar="URL",
        help="trainer status sidecar whose /train.json the "
             "/training.html view follows (default: "
             "PIO_TPU_TRAIN_STATUS_URL)",
    )
    a.set_defaults(fn=cmd_dashboard)

    a = sub.add_parser(
        "fleet", help="run the fleet telemetry aggregator"
    )
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=7000)
    a.add_argument(
        "--targets", default=None, metavar="HOST:PORT,...",
        help="comma list of member servers to scrape (falls back to "
             "PIO_TPU_FLEET_TARGETS)",
    )
    a.add_argument(
        "--interval", type=float, default=None, metavar="SECONDS",
        help="scrape interval (default 5s, jittered; also "
             "PIO_TPU_FLEET_INTERVAL_S)",
    )
    a.set_defaults(fn=cmd_fleet)

    a = sub.add_parser(
        "route", help="run the serving router (multi-host front tier)"
    )
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=8500)
    a.add_argument(
        "--targets", default=None, metavar="HOST:PORT,...",
        help="comma list of serving members to route across (falls back "
             "to PIO_TPU_FLEET_TARGETS)",
    )
    a.add_argument(
        "--partitions", type=int, default=None, metavar="N",
        help="partlog partition count for entity co-location (affinity "
             "engages when it matches the member count)",
    )
    a.add_argument(
        "--interval", type=float, default=None, metavar="SECONDS",
        help="member scrape interval (default 5s, jittered; also "
             "PIO_TPU_FLEET_INTERVAL_S)",
    )
    a.add_argument(
        "--timeout", type=float, default=5.0, metavar="SECONDS",
        help="upstream forward timeout per attempt (default 5s)",
    )
    a.add_argument(
        "--admin-key", default=None,
        help="bearer key for /deploy (loopback-only without one); also "
             "sent member-ward on deploy pushes",
    )
    a.add_argument(
        "--deploy", default=None, metavar="INSTANCE_ID",
        help="client mode: push a manifest-verified rollout of this "
             "engine instance through the router at --url, then exit",
    )
    a.add_argument(
        "--url", default="http://127.0.0.1:8500", metavar="URL",
        help="router base URL for --deploy (default localhost:8500)",
    )
    a.set_defaults(fn=cmd_route)

    a = sub.add_parser(
        "rollout",
        help="progressive delivery: shadow/canary a candidate instance "
             "through a running router",
    )
    a.add_argument(
        "--url", default="http://127.0.0.1:8500", metavar="URL",
        help="router base URL (default localhost:8500)",
    )
    a.add_argument(
        "--start", default=None, metavar="INSTANCE_ID",
        help="start a rollout of this candidate engine instance",
    )
    a.add_argument(
        "--abort", action="store_true",
        help="abort the live rollout (immediate incumbent rollback)",
    )
    a.add_argument(
        "--targets", default=None, metavar="HOST:PORT,...",
        help="candidate serving members for --start",
    )
    a.add_argument(
        "--incumbent", default=None, metavar="INSTANCE_ID",
        help="pin the incumbent instance (default: discovered from the "
             "ring members' GET /deploy.json)",
    )
    a.add_argument("--shadow-rate", type=float, default=None,
                   metavar="FRACTION",
                   help="fraction of live traffic mirrored (default 0.25)")
    a.add_argument("--shadow-min-samples", type=int, default=None,
                   metavar="N")
    a.add_argument("--shadow-hold", type=float, default=None,
                   metavar="SECONDS")
    a.add_argument("--canary-fraction", type=float, default=None,
                   metavar="FRACTION",
                   help="keyspace fraction served by the candidate "
                        "during canary (default 0.1)")
    a.add_argument("--canary-hold", type=float, default=None,
                   metavar="SECONDS")
    a.add_argument("--canary-min-requests", type=int, default=None,
                   metavar="N")
    a.add_argument("--judge-interval", type=float, default=None,
                   metavar="SECONDS")
    a.add_argument("--judge-fast", type=float, default=None,
                   metavar="SECONDS",
                   help="fast burn window (default 30s)")
    a.add_argument("--judge-slow", type=float, default=None,
                   metavar="SECONDS",
                   help="slow burn window (default 120s)")
    a.add_argument("--burn-limit", type=float, default=None,
                   metavar="RATE")
    a.add_argument("--mismatch-limit", type=float, default=None,
                   metavar="FRACTION")
    a.add_argument(
        "--admin-key", default=None,
        help="bearer key when the router requires one",
    )
    a.add_argument(
        "--timeout", type=float, default=10.0, metavar="SECONDS",
    )
    a.set_defaults(fn=cmd_rollout)

    a = sub.add_parser("adminserver", help="run the admin REST API")
    a.add_argument("--ip", default="0.0.0.0")
    a.add_argument("--port", type=int, default=7071)
    a.add_argument(
        "--admin-key", default=None,
        help="access key required for mutating routes; without one they "
             "are loopback-only",
    )
    a.set_defaults(fn=cmd_adminserver)

    a = sub.add_parser("import", help="import JSON-lines events")
    a.add_argument("--app", required=True)
    a.add_argument("--input", required=True)
    a.add_argument("--channel", default=None)
    a.set_defaults(fn=cmd_import)

    a = sub.add_parser("export", help="export events as JSON-lines")
    a.add_argument("--app", required=True)
    a.add_argument("--output", required=True)
    a.add_argument("--channel", default=None)
    a.set_defaults(fn=cmd_export)

    sub.add_parser("status", help="storage/device health check").set_defaults(
        fn=cmd_status
    )
    a = sub.add_parser(
        "top", help="live per-device HBM + compile table from /device.json"
    )
    a.add_argument(
        "--url", default="http://127.0.0.1:8000", metavar="URL",
        help="query server or trainer status sidecar base URL",
    )
    a.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="poll interval in seconds (default 2.0)",
    )
    a.add_argument(
        "--once", action="store_true",
        help="print one snapshot and exit (no screen clearing)",
    )
    a.add_argument(
        "-n", "--iterations", type=int, default=0, metavar="N",
        help="stop after N refreshes (0 = run until interrupted)",
    )
    a.set_defaults(fn=cmd_top)
    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser(
        "shell", help="interactive Python shell with stores preloaded"
    ).set_defaults(fn=cmd_shell)
    t = sub.add_parser("template", help="bundled engine templates").add_subparsers(
        dest="template_verb", required=True
    )
    t.add_parser("list").set_defaults(fn=cmd_template_list)

    a = sub.add_parser(
        "upgrade", help="migrate storage to this build's schema"
    )
    a.add_argument(
        "--rebuild-search-index", action="store_true",
        help="drop + refill searchable stores' FTS indexes "
             "(run after an out-of-band VACUUM)",
    )
    a.set_defaults(fn=cmd_upgrade)

    a = sub.add_parser(
        "run", help="run a module:function entry point with the framework"
    )
    a.add_argument("target", help="entry point as module:function")
    a.add_argument(
        "args", nargs=argparse.REMAINDER,
        help="passthrough arguments (everything after the target, "
             "flag-like tokens included)",
    )
    a.set_defaults(fn=cmd_run)

    a = sub.add_parser(
        "lint",
        help="project-native static analysis (concurrency + conventions)",
    )
    a.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: pio_tpu tests)",
    )
    a.add_argument("--json", action="store_true", help="JSON findings")
    a.add_argument(
        "--rules", default=None, metavar="ID[,ID…]",
        help="run only these rule ids",
    )
    a.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    a.add_argument(
        "--dump-failpoints", action="store_true",
        help="machine-readable inventory of failpoint() call sites "
             "(cross-check chaos specs against real points)",
    )
    a.add_argument(
        "--dump-callgraph", action="store_true",
        help="resolved call edges (caller -> callees) as JSON",
    )
    a.add_argument(
        "--dump-effects", action="store_true",
        help="hot-path roots, per-function effect summaries and "
             "frame-family census as JSON",
    )
    a.add_argument(
        "--dump-contracts", action="store_true",
        help="extracted cross-surface inventory as JSON: endpoint "
             "payload keys with producers/consumers, X-Pio-* header "
             "flows, and PIO_TPU_* knob sites joined against the "
             "canonical registry",
    )
    a.add_argument(
        "--changed", action="store_true",
        help="report findings only for files in `git diff --name-only "
             "<base>` (whole tree still loads for call-graph context)",
    )
    a.add_argument(
        "--base", default="HEAD", metavar="REV",
        help="diff base for --changed (default: HEAD)",
    )
    a.set_defaults(fn=cmd_lint)
    return p


def _configure_logging(verbosity: int) -> None:
    """Console logging for CLI runs (reference log4j.properties +
    ``WorkflowUtils.modifyLogging``): pio_tpu at INFO by default so
    training status, checkpoint restores, and server events are visible;
    -q → WARNING, -v → DEBUG (jax stays at WARNING unless -v)."""
    level = (
        logging.WARNING if verbosity < 0
        else logging.DEBUG if verbosity > 0
        else logging.INFO
    )
    logging.basicConfig(format="[%(levelname)s] [%(name)s] %(message)s")
    logging.getLogger("pio_tpu").setLevel(level)
    logging.getLogger("jax").setLevel(
        logging.DEBUG if verbosity > 0 else logging.WARNING
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _configure_logging(-1 if args.quiet else args.verbose)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
