"""HTTP servers: Event Server (ingest) + Query Server (per-engine serving).

Rebuild of the reference's ``data/.../data/api/EventServer.scala`` and
``core/.../workflow/CreateServer.scala`` (UNVERIFIED paths; see SURVEY.md).
"""

from pio_tpu.server.event_server import EventServerService, create_event_server
from pio_tpu.server.http import JsonHTTPServer, Router
from pio_tpu.server.query_server import (
    QueryServerService,
    create_query_server,
)

__all__ = [
    "EventServerService",
    "JsonHTTPServer",
    "QueryServerService",
    "Router",
    "create_event_server",
    "create_query_server",
]
