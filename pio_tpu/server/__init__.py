"""HTTP servers: Event Server (ingest) + Query Server (per-engine serving).

Rebuild of the reference's ``data/.../data/api/EventServer.scala`` and
``core/.../workflow/CreateServer.scala`` (UNVERIFIED paths; see SURVEY.md).
"""

from pio_tpu.server.admin import AdminService, create_admin_server
from pio_tpu.server.plugins import (
    EngineServerPlugin,
    EventServerPlugin,
    clear_plugins,
    installed_plugins,
    load_plugins_from_env,
    register_plugin,
)
from pio_tpu.server.dashboard import DashboardService, create_dashboard
from pio_tpu.server.event_server import EventServerService, create_event_server
from pio_tpu.server.http import JsonHTTPServer, RawResponse, Router
from pio_tpu.server.query_server import (
    QueryServerService,
    create_query_server,
)

__all__ = [
    "AdminService",
    "DashboardService",
    "EngineServerPlugin",
    "EventServerPlugin",
    "clear_plugins",
    "installed_plugins",
    "load_plugins_from_env",
    "register_plugin",
    "EventServerService",
    "JsonHTTPServer",
    "QueryServerService",
    "RawResponse",
    "Router",
    "create_admin_server",
    "create_dashboard",
    "create_event_server",
    "create_query_server",
]
