"""Minimal threaded HTTP JSON server shared by the Event and Query servers.

The reference runs spray [v0.11] / akka-http [v0.12] actor systems; here a
stdlib ``ThreadingHTTPServer`` with a route table does the same job with no
external dependencies. Handlers receive a :class:`Request` and return
``(status, json_body)``.

The query server can swap this thread-per-connection front for the
selectors event loop in :mod:`pio_tpu.server.evfront`
(``PIO_TPU_HTTP_FRONT=evloop``); both fronts share the Router/Request
contract, the response head caches, and the knobs below, so handlers
never know which front carried them.
"""

from __future__ import annotations

import hmac
import json
import logging
import os
import re
import socket
import socketserver
import ssl
import tempfile
import threading
import time
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer
from typing import Any, BinaryIO, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from pio_tpu.utils import knobs
from pio_tpu.obs.metrics import monotonic_s
from pio_tpu.utils import envutil

log = logging.getLogger("pio_tpu.server")


def _env_float(name: str, default: float) -> float:
    """Positive float from the environment, falling back (with a
    warning) on a malformed value — a typo'd limit must degrade to the
    default, not kill every server at import time. (The general helpers
    live in :mod:`pio_tpu.utils.envutil`; body caps are always
    positive.)"""
    return envutil.env_float(name, default, positive=True)


#: Reject request bodies above this many MiB with 413 (configurable —
#: model artifacts PUT to the blob daemon can be large, but an unbounded
#: body is a trivial memory/disk DoS on any network-facing server).
MAX_BODY_MB = _env_float("PIO_TPU_MAX_BODY_MB", 4096.0)

#: Octet-stream bodies above this spill from memory to a temp file while
#: being read off the socket (the blob daemon's PUT path — a multi-GB
#: artifact must not be buffered per request).
_SPOOL_BYTES = 8 << 20

#: Structured (JSON/form) bodies are parsed in memory, so they get a much
#: tighter cap than raw octet-stream uploads — without it, a request with
#: a non-binary Content-Type and a huge Content-Length would be buffered
#: whole in RAM before any handler (or auth) ran.
MAX_JSON_BODY_MB = _env_float("PIO_TPU_MAX_JSON_BODY_MB", 64.0)


def http_backlog() -> int:
    """Listen backlog shared by both HTTP fronts, read at server
    construction (not import) so one process can honor a changed env
    between server boots. socketserver's default of 5 overflowed under
    a 16-client connect burst; 128 keeps dropped-SYN retransmits out of
    the serving p95."""
    return knobs.knob_int("PIO_TPU_HTTP_BACKLOG")


def http_idle_timeout_s() -> float:
    """Idle/slowloris guard shared by both fronts: a connection that
    produces no bytes for this long is closed. On the threaded front it
    bounds how long a parked per-connection thread survives; on the
    event loop it bounds the connection table."""
    return knobs.knob_float("PIO_TPU_HTTP_IDLE_TIMEOUT_S")


#: Content type of the packed int8 binary query wire: the request body
#: IS a batch-lane frame (``pack_query_i8`` layout — NUL-led magic +
#: dim + codes). Both fronts hand it to the handler untouched via
#: :attr:`Request.packed` — no JSON attempt, no decode; the event-loop
#: front passes a zero-copy view into its connection buffer.
PACKED_QUERY_CONTENT_TYPE = "application/x-pio-query-i8"


def keys_equal(provided: str, expected: str) -> bool:
    """Constant-time access-key comparison (no prefix-length timing leak)."""
    return hmac.compare_digest(
        provided.encode("utf-8", "replace"), expected.encode("utf-8", "replace")
    )


@dataclass
class Request:
    method: str
    path: str
    params: Dict[str, str]
    body: Optional[Any]  # parsed JSON (or raw str for form posts)
    raw_body: bytes = b""
    #: large octet-stream bodies arrive here (spooled, seeked to 0)
    #: instead of raw_body — closed by the server after the handler runs
    body_file: Optional[BinaryIO] = None
    #: header names lowercased (HTTP/2-origin clients send lowercase)
    headers: Dict[str, str] = field(default_factory=dict)
    path_args: Tuple[str, ...] = ()
    client_addr: str = ""
    #: handler-settable hook invoked AFTER the response is written to the
    #: socket — for actions that must not race the reply (e.g. /undeploy
    #: stopping the server)
    after_response: Optional[Callable[[], None]] = None
    #: seconds spent reading + parsing this request off the socket (first
    #: request-line byte → body parsed) — the "accept" stage of a latency
    #: waterfall. Excludes keep-alive idle wait before the request line.
    read_s: float = 0.0
    #: handler-settable hook called with the response-write duration in
    #: seconds once the reply is flushed — the "write" stage (the handler
    #: has long returned by then, so tracing needs a callback)
    on_written: Optional[Callable[[float], None]] = None
    #: body bytes of a :data:`PACKED_QUERY_CONTENT_TYPE` request —
    #: ``bytes`` from the threaded front, a ``memoryview`` into the
    #: connection's read buffer from the event loop (valid only for the
    #: duration of the handler call; the front reclaims the buffer after
    #: dispatch). ``body``/``raw_body`` stay empty for these requests.
    packed: Optional[Any] = None

    def header(self, name: str, default: Optional[str] = None):
        return self.headers.get(name.lower(), default)

    def bearer_key(self) -> str:
        """Access key from ?accessKey= or the Authorization header."""
        key = self.params.get("accessKey") or self.header("Authorization", "")
        if key.startswith("Bearer "):
            key = key[len("Bearer "):]
        return key


@dataclass
class RawResponse:
    """Non-JSON handler output (HTML pages, plain text, raw bytes — the
    blob daemon serves binary model artifacts — plus extra headers)."""

    body: Any  # str or bytes
    content_type: str = "text/html; charset=UTF-8"
    headers: Dict[str, str] = field(default_factory=dict)


@dataclass
class FileResponse:
    """Stream a file to the client in constant memory (the blob daemon's
    GET path — a multi-GB model artifact must not be buffered per
    request). The file is opened at response time; a vanished file
    becomes a 404."""

    path: str
    content_type: str = "application/octet-stream"
    chunk_size: int = 1 << 20


Handler = Callable[[Request], Tuple[int, Any]]


class HTTPError(Exception):
    """Handler-raised error. ``headers`` (optional) are emitted on the
    response — the QoS layer needs ``Retry-After`` on its 429/503s."""

    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = dict(headers) if headers else {}


def json_response(body: Any, headers: Dict[str, str]) -> RawResponse:
    """A JSON body that must carry extra headers (the plain dict path
    through ``_respond`` can't — e.g. ``X-Pio-Degraded`` stale serves)."""
    return RawResponse(
        json.dumps(body),
        content_type="application/json; charset=UTF-8",
        headers=headers,
    )


#: Prometheus scrape content type (text format 0.0.4).
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metrics_response(lines: List[str]) -> RawResponse:
    """Wrap exposition lines in the Prometheus scrape content type —
    the one ``GET /metrics`` handler body every server shares."""
    return RawResponse(
        "\n".join(lines) + "\n", content_type=METRICS_CONTENT_TYPE
    )


def int_param(params: Dict[str, str], name: str, default: int,
              lo: Optional[int] = None,
              hi: Optional[int] = None) -> int:
    """Validated integer query param: non-integer or below ``lo`` → 400
    (a typo'd ``?n=`` must not silently fall back to the default, and a
    negative count is a client error, not an empty result); values above
    ``hi`` clamp (asking for more than the ring holds is well-defined)."""
    raw = params.get(name)
    if raw is None:
        return default
    try:
        v = int(raw)
    except (TypeError, ValueError):
        raise HTTPError(400, f"query param {name}={raw!r} is not an integer")
    if lo is not None and v < lo:
        raise HTTPError(400, f"query param {name} must be >= {lo}")
    if hi is not None and v > hi:
        v = hi
    return v


def float_param(params: Dict[str, str], name: str, default: float,
                lo: Optional[float] = None) -> float:
    """Validated float query param — same contract as :func:`int_param`
    (``/stats.json?window=abc`` is a 400, not a silent cumulative view)."""
    raw = params.get(name)
    if raw is None:
        return default
    try:
        v = float(raw)
    except (TypeError, ValueError):
        raise HTTPError(400, f"query param {name}={raw!r} is not a number")
    if v != v:  # NaN compares unequal to itself
        raise HTTPError(400, f"query param {name} must be a finite number")
    if lo is not None and v < lo:
        raise HTTPError(400, f"query param {name} must be >= {lo:g}")
    return v


class Router:
    """Method+regex route table."""

    def __init__(self):
        self._routes: List[Tuple[str, re.Pattern, Handler]] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        self._routes.append((method, re.compile(f"^{pattern}$"), handler))

    def dispatch(self, req: Request) -> Tuple[int, Any]:
        for method, pattern, handler in self._routes:
            if method != req.method:
                continue
            m = pattern.match(req.path)
            if m:
                req.path_args = m.groups()
                return handler(req)
        return 404, {"message": f"no route for {req.method} {req.path}"}


_REASONS = {
    200: "OK", 201: "Created", 204: "No Content", 301: "Moved Permanently",
    302: "Found", 304: "Not Modified", 400: "Bad Request",
    401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 411: "Length Required",
    413: "Content Too Large", 429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error", 503: "Service Unavailable",
}

_ALLOWED_METHODS = frozenset(
    {"GET", "POST", "PUT", "DELETE", "HEAD", "OPTIONS", "PATCH"}
)

_date_cache: Tuple[int, str] = (0, "")


def _http_date() -> str:
    """RFC 9110 Date header value, recomputed at most once per second —
    ``email.utils.formatdate`` costs more than the rest of a response."""
    global _date_cache
    now = int(time.time())  # pio: disable=wallclock-duration (Date header)
    if _date_cache[0] != now:
        import email.utils

        _date_cache = (now, email.utils.formatdate(now, usegmt=True))
    return _date_cache[1]


_date_line_cache: Tuple[int, bytes] = (0, b"")


def _http_date_line() -> bytes:
    """Pre-encoded ``Date: ...\\r\\n`` header line, same 1 s cache."""
    global _date_line_cache
    now = int(time.time())  # pio: disable=wallclock-duration (Date header)
    if _date_line_cache[0] != now:
        _date_line_cache = (
            now, b"Date: " + _http_date().encode("latin-1") + b"\r\n"
        )
    return _date_line_cache[1]


#: pre-encoded Content-Type lines — the JSON type covers ~every response
_ctype_line_cache: dict = {}


def _ctype_line(ctype: str) -> bytes:
    got = _ctype_line_cache.get(ctype)
    if got is None:
        got = f"Content-Type: {ctype}\r\n".encode("latin-1")
        _ctype_line_cache[ctype] = got
    return got


#: per-THREAD response serialize buffer: head + payload assemble here
#: and hit the socket as one write, reused across requests with no
#: per-response allocation. Thread-local, NOT per-connection: handlers
#: are not strictly confined to their accept thread (the batch-lane
#: drainer answers laned requests from its own thread), and a shared
#: bytearray would interleave two responses' bytes. The single-threaded
#: event-loop front cannot use this at all — one thread serves every
#: connection, so it keeps a write buffer PER CONNECTION instead (see
#: evfront._Conn.obuf); sharing this one would alias pipelined
#: responses across connections.
_obuf_local = threading.local()


def _thread_obuf() -> bytearray:
    buf = getattr(_obuf_local, "buf", None)
    if buf is None:
        buf = _obuf_local.buf = bytearray()
    return buf


def _make_handler_class(
    router: Router,
    server_name: str,
    pre_body: Optional[Callable[[Request], None]] = None,
    large_uploads: bool = False,
):
    """Per-connection handler with a hand-rolled HTTP/1.1 parser.

    ``http.server``'s ``BaseHTTPRequestHandler`` parses headers through
    ``email.parser`` — measured ~200 µs per request, about half the total
    server-side cost on this stack's single-core serving path. This
    handler reads the request line and headers with plain ``readline`` +
    ``partition`` and writes each response as one buffered payload, which
    also keeps the round-3 Nagle/keep-alive discipline (single write per
    response, TCP_NODELAY on).
    """

    # status line + Server header never change for this server instance:
    # encode once per status instead of re-building the f-string (and
    # re-encoding) on every response
    _static_head: dict = {}

    def _head_prefix(status: int) -> bytes:
        got = _static_head.get(status)
        if got is None:
            got = (
                f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"
                f"Server: {server_name}\r\n"
            ).encode("latin-1")
            _static_head[status] = got
        return got

    class JsonHandler(socketserver.StreamRequestHandler):
        rbufsize = 64 * 1024
        wbufsize = 64 * 1024
        disable_nagle_algorithm = True
        # socket timeout = the shared idle/slowloris guard: a keep-alive
        # connection (or a stalled mid-request read) that produces no
        # bytes within the window raises and the thread exits instead of
        # parking forever. Read once per server construction.
        timeout = http_idle_timeout_s()

        command = ""  # current request method (HEAD gates body writes)
        http10 = False  # current request is HTTP/1.0 (keep-alive echo)

        def handle(self):
            self.close_connection = False
            try:
                while not self.close_connection:
                    if not self._handle_one():
                        break
            except OSError:  # covers ConnectionError and TimeoutError
                pass

        # -- response writing ------------------------------------------
        def _head_into(self, buf: bytearray, status, ctype, length,
                       extra=()) -> None:
            buf += _head_prefix(status)
            buf += _http_date_line()
            buf += _ctype_line(ctype)
            buf += b"Content-Length: %d\r\n" % length
            for k, v in extra:
                buf += f"{k}: {v}\r\n".encode("latin-1")
            if self.close_connection:
                buf += b"Connection: close\r\n"
            elif self.http10:
                # an HTTP/1.0 client assumes close unless keep-alive is
                # echoed back — without this it would never reuse the
                # connection while we block in readline waiting for it
                buf += b"Connection: keep-alive\r\n"
            buf += b"\r\n"

        def _head_bytes(self, status, ctype, length, extra=()) -> bytes:
            out = bytearray()
            self._head_into(out, status, ctype, length, extra)
            return bytes(out)

        def _respond(self, status: int, body: Any):
            # HEAD must carry Content-Length but NO body bytes — writing
            # them would desync keep-alive clients (RFC 9110 §9.3.2)
            head = self.command == "HEAD"
            if isinstance(body, FileResponse):
                try:
                    f = open(body.path, "rb")
                except OSError:
                    self._respond(404, {"message": "no such blob"})
                    return
                with f:
                    size = os.fstat(f.fileno()).st_size
                    self.wfile.write(
                        self._head_bytes(status, body.content_type, size)
                    )
                    if not head:
                        while chunk := f.read(body.chunk_size):
                            self.wfile.write(chunk)
                self.wfile.flush()
                return
            out = _thread_obuf()
            del out[:]
            if isinstance(body, RawResponse):
                payload = (
                    body.body if isinstance(body.body, bytes)
                    else body.body.encode()
                )
                self._head_into(
                    out, status, body.content_type, len(payload),
                    body.headers.items(),
                )
                if not head:
                    out += payload
                self.wfile.write(out)
                self.wfile.flush()
                return
            try:
                payload = json.dumps(body).encode() if body is not None else b""
            except (TypeError, ValueError):
                # Un-serializable handler output must still produce an HTTP
                # response, not a dropped connection.
                log.exception("response not JSON-serializable")
                status = 500
                payload = b'{"message": "response not JSON-serializable"}'
            self._head_into(
                out, status, "application/json; charset=UTF-8", len(payload)
            )
            if payload and not head:
                out += payload
            self.wfile.write(out)
            self.wfile.flush()

        def _reject(self, status: int, message: str) -> bool:
            """Terminal error response: close the connection after it."""
            self.close_connection = True
            self._respond(status, {"message": message})
            return False

        # -- request parsing -------------------------------------------
        def _handle_one(self) -> bool:
            self.command = ""
            line = self.rfile.readline(65537)
            if not line:
                return False  # client closed the keep-alive connection
            # the accept clock starts once the request line has arrived —
            # keep-alive idle time between requests is not request latency
            t_accept = monotonic_s()
            if len(line) > 65536:
                return self._reject(400, "request line too long")
            line = line.strip()
            if not line:
                return True  # stray CRLF between requests — tolerated
            parts = line.split()
            if len(parts) != 3:
                return self._reject(400, "malformed request line")
            try:
                method = parts[0].decode("ascii")
                target = parts[1].decode("latin-1")
            except UnicodeDecodeError:
                return self._reject(400, "malformed request line")
            version = parts[2]
            if not version.startswith(b"HTTP/1."):
                return self._reject(400, "unsupported HTTP version")
            if method not in _ALLOWED_METHODS:
                return self._reject(405, f"method {method!r} not allowed")

            headers: Dict[str, str] = {}
            last = None
            for _ in range(200):
                hline = self.rfile.readline(65537)
                if not hline:
                    return False  # peer vanished mid-headers
                if len(hline) > 65536:
                    return self._reject(431, "header line too long")
                if hline in (b"\r\n", b"\n"):
                    break
                if hline[:1] in (b" ", b"\t"):
                    # RFC 9112 obs-fold continuation line
                    if last is not None:
                        headers[last] += (
                            " " + hline.strip().decode("latin-1")
                        )
                    continue
                name, sep, value = hline.partition(b":")
                if not sep:
                    return self._reject(400, "malformed header")
                last = name.strip().decode("latin-1").lower()
                val = value.strip().decode("latin-1")
                if last in ("content-length", "transfer-encoding") \
                        and headers.get(last, val) != val:
                    # differing duplicate framing headers are a request-
                    # smuggling primitive behind a proxy (RFC 9112 §6.3)
                    return self._reject(400, f"duplicate {last}")
                headers[last] = val
            else:
                return self._reject(431, "too many headers")

            self.command = method
            conn_tok = headers.get("connection", "").lower()
            self.http10 = version == b"HTTP/1.0"
            if self.http10:
                self.close_connection = "keep-alive" not in conn_tok
            else:
                self.close_connection = "close" in conn_tok
            self._dispatch(method, target, headers, t_accept)
            return not self.close_connection

        def _dispatch(self, method: str, target: str,
                      headers: Dict[str, str],
                      t_accept: Optional[float] = None):
            path, _, query = target.partition("?")
            params = (
                {k: v[0] for k, v in parse_qs(query).items()}
                if query else {}
            )
            if headers.get("transfer-encoding"):
                # Chunked bodies aren't framed by Content-Length; reading
                # them naively corrupts keep-alive framing. Reject + close.
                self._reject(411, "Content-Length required")
                return
            try:
                length = int(headers.get("content-length") or 0)
            except ValueError:
                self._reject(400, "bad Content-Length")
                return
            if length < 0:
                # read(-1) would mean read-to-EOF: a held-open connection
                # pins this thread and the eventual body is garbage
                self._reject(400, "bad Content-Length")
                return
            if length > MAX_BODY_MB * 2 ** 20:
                # can't cheaply drain an over-limit body; close instead
                self._reject(
                    413, f"body exceeds {MAX_BODY_MB:g} MiB limit"
                )
                return
            ctype = headers.get("content-type", "").lower()
            octet = ctype.startswith("application/octet-stream")
            if length and (not octet or not large_uploads) \
                    and length > MAX_JSON_BODY_MB * 2 ** 20:
                # structured bodies are parsed in RAM — cap them far
                # below the raw-upload limit (a big Content-Length with
                # a JSON Content-Type must not buffer gigabytes). The
                # same cap covers octet-stream bodies unless the server
                # opted into large uploads (only the blob server, whose
                # pre_body auth runs before any body byte is consumed):
                # otherwise each connection could spool MAX_BODY_MB of
                # unauthenticated bytes to disk
                self._reject(
                    413,
                    f"body exceeds {MAX_JSON_BODY_MB:g} MiB limit "
                    f"for {ctype or 'structured'} content",
                )
                return
            body_file = None
            if length and pre_body is not None:
                # auth runs BEFORE consuming ANY body, or an
                # unauthenticated client could burn disk/bandwidth/RAM
                # up to the body limit per request
                try:
                    pre_body(Request(
                        method=method, path=path, params=params,
                        body=None, headers=headers,
                        client_addr=self.client_address[0],
                    ))
                except HTTPError as e:
                    self._reject(e.status, e.message)  # body unread
                    return
                except Exception:
                    # a pre_body bug must produce an HTTP response, not
                    # a dropped connection + raw socketserver traceback
                    log.exception("pre_body hook failed")
                    self._reject(500, "internal server error")
                    return
            if length and headers.get(
                "expect", ""
            ).lower().startswith("100-continue"):
                # invite the body only AFTER the size caps and pre-body
                # auth all passed — an early 100 Continue would ask a
                # soon-to-be-rejected client to stream its whole upload
                self.wfile.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                self.wfile.flush()
            if length and octet:
                # binary upload (blob daemon): spool off the socket in
                # chunks — never hold a multi-GB artifact in memory
                body_file = tempfile.SpooledTemporaryFile(
                    max_size=_SPOOL_BYTES
                )
                remaining = length
                while remaining:
                    chunk = self.rfile.read(min(remaining, 1 << 20))
                    if not chunk:
                        break
                    body_file.write(chunk)
                    remaining -= len(chunk)
                if remaining:
                    # client died mid-upload: dispatching the truncated
                    # body would store a short artifact with a 201
                    body_file.close()
                    self._reject(400, "incomplete body")
                    return
                body_file.seek(0)
                raw = b""
            else:
                raw = self.rfile.read(length) if length else b""
                if len(raw) < length:
                    self._reject(400, "incomplete body")
                    return
            body = None
            packed = None
            if raw and ctype.startswith(PACKED_QUERY_CONTENT_TYPE):
                # packed binary query wire: the body is a lane frame —
                # no JSON attempt, no text decode; the handler consumes
                # req.packed (parity twin of the event-loop fast path)
                packed = raw
                raw = b""
            elif raw:
                # Try JSON regardless of Content-Type — real clients (curl
                # -d without -H) post JSON bodies under the default form
                # type. Non-JSON bodies stay raw strings; handlers that
                # need JSON objects reject those with a 400, and the
                # webhook .form routes read raw_body directly.
                try:
                    body = json.loads(raw.decode("utf-8"))
                except (UnicodeDecodeError, json.JSONDecodeError):
                    body = raw.decode("utf-8", errors="replace")
            req = Request(
                method=method,
                path=path,
                params=params,
                body=body,
                raw_body=raw,
                body_file=body_file,
                headers=headers,
                client_addr=self.client_address[0],
                packed=packed,
            )
            if t_accept is not None:
                req.read_s = monotonic_s() - t_accept
            try:
                status, out = router.dispatch(req)
            except HTTPError as e:
                status = e.status
                out = (
                    json_response({"message": e.message}, e.headers)
                    if e.headers else {"message": e.message}
                )
            except Exception:
                log.exception("unhandled error on %s %s", method, path)
                status, out = 500, {"message": "internal server error"}
            finally:
                if body_file is not None:
                    body_file.close()
            t_write = monotonic_s()
            self._respond(status, out)
            if req.on_written is not None:
                try:
                    self.wfile.flush()
                except OSError:
                    pass
                try:
                    req.on_written(monotonic_s() - t_write)
                except Exception:
                    log.exception("on_written hook failed")
            if req.after_response is not None:
                try:
                    self.wfile.flush()
                except OSError:
                    pass
                req.after_response()

    return JsonHandler


def ssl_context_from_env() -> Optional[ssl.SSLContext]:
    """TLS config from the environment, or None for plain HTTP.

    Rebuild of the reference's ``common/.../SSLConfiguration.scala``
    (UNVERIFIED path; SURVEY.md §2.5), which reads a JKS keystore from
    config; here: ``PIO_TPU_SSL_CERTFILE`` + ``PIO_TPU_SSL_KEYFILE``
    (PEM paths, keyfile optional if the cert bundles the key) switch every
    server built through :class:`JsonHTTPServer` to HTTPS.
    """
    cert = knobs.knob_str("PIO_TPU_SSL_CERTFILE")
    if not cert:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, knobs.knob_str("PIO_TPU_SSL_KEYFILE") or None)
    return ctx


#: default sentinel: "no explicit context given — consult the env".
#: Distinct from None, which explicitly forces plain HTTP even when the
#: PIO_TPU_SSL_* vars are set (e.g. an internal loopback endpoint beside a
#: public HTTPS server).
SSL_FROM_ENV: Any = object()


class _TLSThreadingHTTPServer(ThreadingHTTPServer):
    """TLS wrapped per connection, in the worker thread.

    Wrapping the LISTENING socket would run the blocking handshake inside
    the single accept loop — one client that connects and never sends a
    ClientHello would stall every other connection. ``finish_request``
    runs in the per-connection thread, so a stalled handshake costs only
    its own thread.
    """

    ssl_ctx: Optional[ssl.SSLContext] = None
    handshake_timeout = 30.0
    #: listen backlog (socketserver default is 5 — a 16-client burst
    #: overflows it and the dropped SYNs retransmit after ~1 s, which
    #: shows up directly as a serving p95 spike under concurrent load);
    #: overwritten per instance from PIO_TPU_HTTP_BACKLOG in
    #: JsonHTTPServer.__init__, kept as a class default for direct users
    request_queue_size = 128
    #: SO_REUSEPORT before bind — lets N worker processes share one port
    #: with kernel-level connection balancing (serving pool mode)
    reuse_port = False

    def server_bind(self):
        if self.reuse_port:
            self.socket.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
            )
        super().server_bind()

    def finish_request(self, request, client_address):
        if self.ssl_ctx is None:
            super().finish_request(request, client_address)
            return
        prev = request.gettimeout()
        try:
            request.settimeout(self.handshake_timeout)
            tls_sock = self.ssl_ctx.wrap_socket(request, server_side=True)
            tls_sock.settimeout(prev)
        except (OSError, ssl.SSLError) as e:  # bad/absent handshake
            log.debug("TLS handshake failed from %s: %s", client_address, e)
            try:
                request.close()
            except OSError:
                pass
            return
        try:
            super().finish_request(tls_sock, client_address)
        finally:
            # wrap_socket detached the original socket, so the outer
            # shutdown_request can't close this fd — do it here
            try:
                tls_sock.close()
            except OSError:
                pass


class JsonHTTPServer:
    """Threaded server with programmatic start/stop (tests + CLI)."""

    def __init__(self, router: Router, host: str = "0.0.0.0", port: int = 0,
                 name: str = "pio-tpu",
                 ssl_context: Any = SSL_FROM_ENV,
                 pre_body: Optional[Callable[[Request], None]] = None,
                 reuse_port: bool = False,
                 large_uploads: bool = False):
        self._httpd = _TLSThreadingHTTPServer(
            (host, port),
            _make_handler_class(router, name, pre_body, large_uploads),
            bind_and_activate=False,
        )
        self._httpd.reuse_port = reuse_port
        self._httpd.request_queue_size = http_backlog()
        try:
            self._httpd.server_bind()
            self._httpd.server_activate()
        except BaseException:
            self._httpd.server_close()
            raise
        ctx = (
            ssl_context_from_env()
            if ssl_context is SSL_FROM_ENV
            else ssl_context
        )
        self.tls = ctx is not None
        self._httpd.ssl_ctx = ctx
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "JsonHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def stop(self):
        if getattr(self, "_stopped", False):
            return  # idempotent: /undeploy and a pool supervisor may race
        self._stopped = True
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
