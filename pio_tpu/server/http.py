"""Minimal threaded HTTP JSON server shared by the Event and Query servers.

The reference runs spray [v0.11] / akka-http [v0.12] actor systems; here a
stdlib ``ThreadingHTTPServer`` with a route table does the same job with no
external dependencies. Handlers receive a :class:`Request` and return
``(status, json_body)``.
"""

from __future__ import annotations

import json
import logging
import os
import re
import ssl
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

log = logging.getLogger("pio_tpu.server")


@dataclass
class Request:
    method: str
    path: str
    params: Dict[str, str]
    body: Optional[Any]  # parsed JSON (or raw str for form posts)
    raw_body: bytes = b""
    #: header names lowercased (HTTP/2-origin clients send lowercase)
    headers: Dict[str, str] = field(default_factory=dict)
    path_args: Tuple[str, ...] = ()
    client_addr: str = ""
    #: handler-settable hook invoked AFTER the response is written to the
    #: socket — for actions that must not race the reply (e.g. /undeploy
    #: stopping the server)
    after_response: Optional[Callable[[], None]] = None

    def header(self, name: str, default: Optional[str] = None):
        return self.headers.get(name.lower(), default)

    def bearer_key(self) -> str:
        """Access key from ?accessKey= or the Authorization header."""
        key = self.params.get("accessKey") or self.header("Authorization", "")
        if key.startswith("Bearer "):
            key = key[len("Bearer "):]
        return key


@dataclass
class RawResponse:
    """Non-JSON handler output (HTML pages, plain text, raw bytes — the
    blob daemon serves binary model artifacts — plus extra headers)."""

    body: Any  # str or bytes
    content_type: str = "text/html; charset=UTF-8"
    headers: Dict[str, str] = field(default_factory=dict)


@dataclass
class FileResponse:
    """Stream a file to the client in constant memory (the blob daemon's
    GET path — a multi-GB model artifact must not be buffered per
    request). The file is opened at response time; a vanished file
    becomes a 404."""

    path: str
    content_type: str = "application/octet-stream"
    chunk_size: int = 1 << 20


Handler = Callable[[Request], Tuple[int, Any]]


class HTTPError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class Router:
    """Method+regex route table."""

    def __init__(self):
        self._routes: List[Tuple[str, re.Pattern, Handler]] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        self._routes.append((method, re.compile(f"^{pattern}$"), handler))

    def dispatch(self, req: Request) -> Tuple[int, Any]:
        for method, pattern, handler in self._routes:
            if method != req.method:
                continue
            m = pattern.match(req.path)
            if m:
                req.path_args = m.groups()
                return handler(req)
        return 404, {"message": f"no route for {req.method} {req.path}"}


def _make_handler_class(router: Router, server_name: str):
    class JsonHandler(BaseHTTPRequestHandler):
        server_version = server_name
        protocol_version = "HTTP/1.1"
        # Keep-alive clients stall ~40 ms/request without these: headers
        # and body leave in separate small writes, and Nagle holds the
        # second segment until the client's delayed ACK. Buffer the
        # response into one write (handle_one_request flushes) and turn
        # Nagle off for whatever remains split.
        wbufsize = 64 * 1024
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # route to logging, not stderr
            log.debug("%s %s", self.address_string(), fmt % args)

        def _respond(self, status: int, body: Any):
            # HEAD must carry Content-Length but NO body bytes — writing
            # them would desync keep-alive clients (RFC 9110 §9.3.2)
            head = self.command == "HEAD"
            if isinstance(body, FileResponse):
                try:
                    f = open(body.path, "rb")
                except OSError:
                    self._respond(404, {"message": "no such blob"})
                    return
                with f:
                    size = os.fstat(f.fileno()).st_size
                    self.send_response(status)
                    self.send_header("Content-Type", body.content_type)
                    self.send_header("Content-Length", str(size))
                    self.end_headers()
                    if not head:
                        while chunk := f.read(body.chunk_size):
                            self.wfile.write(chunk)
                return
            if isinstance(body, RawResponse):
                payload = (
                    body.body if isinstance(body.body, bytes)
                    else body.body.encode()
                )
                self.send_response(status)
                self.send_header("Content-Type", body.content_type)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in body.headers.items():
                    self.send_header(k, v)
                self.end_headers()
                if not head:
                    self.wfile.write(payload)
                return
            try:
                payload = json.dumps(body).encode() if body is not None else b""
            except (TypeError, ValueError):
                # Un-serializable handler output must still produce an HTTP
                # response, not a dropped connection.
                log.exception("response not JSON-serializable")
                status = 500
                payload = b'{"message": "response not JSON-serializable"}'
            self.send_response(status)
            self.send_header("Content-Type", "application/json; charset=UTF-8")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            if payload and not head:
                self.wfile.write(payload)

        def _handle(self, method: str):
            parsed = urlparse(self.path)
            params = {
                k: v[0] for k, v in parse_qs(parsed.query).items()
            }
            if self.headers.get("Transfer-Encoding"):
                # Chunked bodies aren't framed by Content-Length; reading them
                # naively corrupts keep-alive framing. Reject and close.
                self.close_connection = True
                self._respond(411, {"message": "Content-Length required"})
                return
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            body = None
            ctype = (self.headers.get("Content-Type") or "").lower()
            if raw and ctype.startswith("application/octet-stream"):
                pass  # binary upload (blob daemon): no decode attempt
            elif raw:
                # Try JSON regardless of Content-Type — real clients (curl
                # -d without -H) post JSON bodies under the default form
                # type. Non-JSON bodies stay raw strings; handlers that
                # need JSON objects reject those with a 400, and the
                # webhook .form routes read raw_body directly.
                try:
                    body = json.loads(raw.decode("utf-8"))
                except (UnicodeDecodeError, json.JSONDecodeError):
                    body = raw.decode("utf-8", errors="replace")
            req = Request(
                method=method,
                path=parsed.path,
                params=params,
                body=body,
                raw_body=raw,
                headers={k.lower(): v for k, v in self.headers.items()},
                client_addr=self.client_address[0],
            )
            try:
                status, out = router.dispatch(req)
            except HTTPError as e:
                status, out = e.status, {"message": e.message}
            except Exception:
                log.exception("unhandled error on %s %s", method, parsed.path)
                status, out = 500, {"message": "internal server error"}
            self._respond(status, out)
            if req.after_response is not None:
                try:
                    self.wfile.flush()
                except OSError:
                    pass
                req.after_response()

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_PUT(self):
            self._handle("PUT")

        def do_HEAD(self):
            self._handle("HEAD")

        def do_DELETE(self):
            self._handle("DELETE")

    return JsonHandler


def ssl_context_from_env() -> Optional[ssl.SSLContext]:
    """TLS config from the environment, or None for plain HTTP.

    Rebuild of the reference's ``common/.../SSLConfiguration.scala``
    (UNVERIFIED path; SURVEY.md §2.5), which reads a JKS keystore from
    config; here: ``PIO_TPU_SSL_CERTFILE`` + ``PIO_TPU_SSL_KEYFILE``
    (PEM paths, keyfile optional if the cert bundles the key) switch every
    server built through :class:`JsonHTTPServer` to HTTPS.
    """
    cert = os.environ.get("PIO_TPU_SSL_CERTFILE")
    if not cert:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, os.environ.get("PIO_TPU_SSL_KEYFILE") or None)
    return ctx


#: default sentinel: "no explicit context given — consult the env".
#: Distinct from None, which explicitly forces plain HTTP even when the
#: PIO_TPU_SSL_* vars are set (e.g. an internal loopback endpoint beside a
#: public HTTPS server).
SSL_FROM_ENV: Any = object()


class _TLSThreadingHTTPServer(ThreadingHTTPServer):
    """TLS wrapped per connection, in the worker thread.

    Wrapping the LISTENING socket would run the blocking handshake inside
    the single accept loop — one client that connects and never sends a
    ClientHello would stall every other connection. ``finish_request``
    runs in the per-connection thread, so a stalled handshake costs only
    its own thread.
    """

    ssl_ctx: Optional[ssl.SSLContext] = None
    handshake_timeout = 30.0
    #: socketserver's default listen backlog is 5 — a 16-client burst
    #: overflows it and the dropped SYNs retransmit after ~1 s, which
    #: shows up directly as a serving p95 spike under concurrent load
    request_queue_size = 128

    def finish_request(self, request, client_address):
        if self.ssl_ctx is None:
            super().finish_request(request, client_address)
            return
        prev = request.gettimeout()
        try:
            request.settimeout(self.handshake_timeout)
            tls_sock = self.ssl_ctx.wrap_socket(request, server_side=True)
            tls_sock.settimeout(prev)
        except (OSError, ssl.SSLError) as e:  # bad/absent handshake
            log.debug("TLS handshake failed from %s: %s", client_address, e)
            try:
                request.close()
            except OSError:
                pass
            return
        try:
            super().finish_request(tls_sock, client_address)
        finally:
            # wrap_socket detached the original socket, so the outer
            # shutdown_request can't close this fd — do it here
            try:
                tls_sock.close()
            except OSError:
                pass


class JsonHTTPServer:
    """Threaded server with programmatic start/stop (tests + CLI)."""

    def __init__(self, router: Router, host: str = "0.0.0.0", port: int = 0,
                 name: str = "pio-tpu",
                 ssl_context: Any = SSL_FROM_ENV):
        self._httpd = _TLSThreadingHTTPServer(
            (host, port), _make_handler_class(router, name)
        )
        ctx = (
            ssl_context_from_env()
            if ssl_context is SSL_FROM_ENV
            else ssl_context
        )
        self.tls = ctx is not None
        self._httpd.ssl_ctx = ctx
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "JsonHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
