"""Selectors/epoll event-loop HTTP/1.1 front for the query server.

The threaded front in :mod:`pio_tpu.server.http` pays a thread per
connection: under the 16-client keep-alive benchmark the per-request
cost is dominated by scheduler wakeups and lock handoffs, not by
parsing or predict (in-process predict is ~0.26 ms while e2e p50 is
~1.17 ms — ROADMAP item 1). This front serves every connection from ONE
loop per process:

* non-blocking accept off a (optionally SO_REUSEPORT-shared) listener,
* keep-alive with pipelining — requests already in the read buffer are
  served back-to-back and their responses coalesce into fewer writes,
* incremental header/body parsing over a per-connection reuse buffer
  (no thread, no readline, no per-request allocations beyond the
  Request itself),
* write-backpressure via the selector (a slow reader gets EVENT_WRITE
  re-arms, and its read interest drops while its output backlog is
  high),
* idle/slowloris timeouts shared with the threaded front
  (``PIO_TPU_HTTP_IDLE_TIMEOUT_S``).

On top of it rides the zero-copy int8 ingest: a request whose
Content-Type is :data:`~pio_tpu.server.http.PACKED_QUERY_CONTENT_TYPE`
is recognized by a fast-path parser that never touches JSON — the body
bytes are handed to the handler as a ``memoryview`` into the
connection's read buffer (:attr:`Request.packed`), and the lane client
writes them straight into the shm ring frame. Socket → lane frame with
no decode, no dict, no ``bytes()`` copy; the ``# pio: hotpath=zerocopy``
marker makes the effect analysis enforce that statically.

Selection: ``PIO_TPU_HTTP_FRONT=evloop`` in
:func:`pio_tpu.server.query_server.create_query_server`. The threaded
front remains the default and is still REQUIRED for TLS termination,
the blob daemon (spooled multi-GB uploads), and the admin/dashboard/
event daemons — this loop only fronts the query hot path.
"""

from __future__ import annotations

import io
import json
import logging
import selectors
import socket
import threading
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs

from pio_tpu.utils import knobs
from pio_tpu.obs.metrics import monotonic_s
from pio_tpu.server import http as _http
from pio_tpu.server.http import (
    FileResponse,
    HTTPError,
    PACKED_QUERY_CONTENT_TYPE,
    RawResponse,
    Request,
    Router,
    SSL_FROM_ENV,
    json_response,
    ssl_context_from_env,
)

log = logging.getLogger("pio_tpu.server.evfront")

#: recv() chunk — large enough that one syscall drains a typical
#: pipelined burst, small enough not to balloon per-connection buffers
_RECV_CHUNK = 64 * 1024

#: output high-water mark: above this many unflushed response bytes the
#: connection stops being read (and parsed) until the kernel drains it —
#: the selector-level backpressure that keeps one slow reader from
#: buffering unbounded responses
_HIGH_WATER = 256 * 1024

#: request line / header line length cap (same as the threaded parser)
_MAX_LINE = 65536
#: header line count cap (same as the threaded parser's range(200))
_MAX_HEADERS = 200


def _packed_view(view, start: int, end: int):  # pio: hotpath=zerocopy
    """The zero-copy hand-off: slice the packed query body out of the
    connection's read buffer as a memoryview. The bytes the client sent
    ARE the bytes ``LaneClient`` writes into the shm ring — no
    ``bytes()`` materialization anywhere between socket and lane frame,
    which the hotpath-zero-copy rule checks from this root."""
    return view[start:end]


class _Conn:
    """Per-connection state: sockets, the read/write reuse buffers, the
    incremental parse cursors, and the post-write callback queue.

    ``obuf`` is PER CONNECTION by design (the threaded front's
    thread-local reuse buffer assumes a thread owns one response at a
    time — false on a single-threaded loop, where a shared buffer would
    interleave pipelined responses across connections)."""

    __slots__ = (
        "sock", "peer", "ibuf", "obuf", "sent_abs", "cbs", "last",
        "closed", "close_after", "mask", "eof",
        # parse cursors (reset per request)
        "hdr_end", "scan_pos", "line_start", "n_lines", "t_req",
        # parsed-header state (None/0 until the header block completes)
        "method", "target", "headers", "length", "http10",
        "body_packed", "body_octet",
    )

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.ibuf = bytearray()
        self.obuf = bytearray()
        self.sent_abs = 0          # total bytes ever sent on this conn
        self.cbs: deque = deque()  # (abs_end, on_written, t_write, after)
        self.last = monotonic_s()
        self.closed = False
        self.close_after = False
        self.mask = 0
        self.eof = False
        self.reset_parse()

    def reset_parse(self) -> None:
        self.hdr_end = -1
        self.scan_pos = 0
        self.line_start = 0
        self.n_lines = 0
        self.t_req = -1.0
        self.method = ""
        self.target = ""
        self.headers = None
        self.length = 0
        self.http10 = False
        self.body_packed = False
        self.body_octet = False


class EvLoopHTTPServer:
    """Drop-in for :class:`~pio_tpu.server.http.JsonHTTPServer` over a
    selectors event loop — same constructor shape, same
    ``port``/``start``/``serve_forever``/``stop`` surface, same Router/
    Request handler contract. Handlers run INLINE in the loop: they must
    be non-blocking (the ``# pio: hotpath`` markers + effect analysis
    enforce this for the query path).

    ``registry`` (optional MetricsRegistry) feeds the HTTP front
    metrics: ``pio_tpu_http_connections_active`` and
    ``pio_tpu_http_pipelined_total``.
    """

    def __init__(self, router: Router, host: str = "0.0.0.0",
                 port: int = 0, name: str = "pio-tpu",
                 ssl_context: Any = SSL_FROM_ENV,
                 pre_body: Optional[Callable[[Request], None]] = None,
                 reuse_port: bool = False,
                 large_uploads: bool = False,
                 registry: Any = None):
        ctx = (ssl_context_from_env() if ssl_context is SSL_FROM_ENV
               else ssl_context)
        if ctx is not None:
            raise ValueError(
                "the evloop front has no TLS path — terminate TLS on the "
                "threaded front (PIO_TPU_HTTP_FRONT=threaded) or a proxy"
            )
        if large_uploads:
            raise ValueError(
                "the evloop front does not spool large uploads — the blob "
                "daemon requires the threaded front"
            )
        self.tls = False
        self._router = router
        self._name = name
        self._pre_body = pre_body
        self._idle_timeout_s = _http.http_idle_timeout_s()
        self._max_pipeline = knobs.knob_int("PIO_TPU_HTTP_MAX_PIPELINE")
        self._static_head: Dict[int, bytes] = {}
        self._conns: Dict[int, _Conn] = {}
        self._sel = selectors.DefaultSelector()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._last_sweep = monotonic_s()

        self._conn_gauge = None
        self._pipelined_total = None
        if registry is not None:
            self._conn_gauge = registry.gauge(
                "pio_tpu_http_connections_active",
                "Open connections on the event-loop HTTP front",
            )
            self._pipelined_total = registry.counter(
                "pio_tpu_http_pipelined_total",
                "Requests served from a read batch behind an earlier "
                "request on the same connection (pipelining depth proxy)",
            )
            # materialize the zero-label cells now: pool workers must
            # create metric cells in a deterministic order for the shm
            # stripe slots to line up across the pool
            self._conn_gauge.set(0.0)
            self._pipelined_total.labels()

        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuse_port:
                lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            lsock.bind((host, port))
            lsock.listen(_http.http_backlog())
            lsock.setblocking(False)
        except BaseException:
            lsock.close()
            raise
        self._lsock = lsock
        # self-wake pipe so stop() (another thread) can break select()
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._sel.register(lsock, selectors.EVENT_READ, data=None)
        self._sel.register(self._waker_r, selectors.EVENT_READ, data="wake")

    # -- lifecycle -----------------------------------------------------

    @property
    def port(self) -> int:
        return self._lsock.getsockname()[1]

    def start(self) -> "EvLoopHTTPServer":
        self._thread = threading.Thread(
            target=self._run, name=f"{self._name}-evloop", daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._run()

    def stop(self) -> None:
        if self._stopped:
            return  # idempotent: /undeploy and a pool supervisor may race
        self._stopped = True
        try:
            self._waker_w.send(b"x")
        except OSError:
            pass
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    # -- the loop ------------------------------------------------------

    def _run(self) -> None:  # pio: hotpath
        """One loop serves every connection: nothing in here (or
        reachable from here) may park — a blocking call stalls every
        other connection on this worker, which is exactly what the
        hotpath-blocking rule rejects statically."""
        try:
            while not self._stopped:
                timeout = min(1.0, self._idle_timeout_s)
                for key, mask in self._sel.select(timeout):
                    if self._stopped:
                        break
                    if key.data is None:
                        self._accept_ready()
                    elif key.data == "wake":
                        try:
                            self._waker_r.recv(64)
                        except OSError:
                            pass
                    else:
                        conn = key.data
                        try:
                            if mask & selectors.EVENT_WRITE \
                                    and not conn.closed:
                                self._on_writable(conn)
                            if mask & selectors.EVENT_READ \
                                    and not conn.closed:
                                self._on_readable(conn)
                        except Exception:
                            log.exception(
                                "connection handling failed (%s)", conn.peer
                            )
                            self._close(conn)
                self._sweep_idle()
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            try:
                self._sel.unregister(self._lsock)
            except (KeyError, ValueError):
                pass
            self._lsock.close()
            for s in (self._waker_r, self._waker_w):
                try:
                    s.close()
                except OSError:
                    pass
            self._sel.close()

    def _sweep_idle(self) -> None:  # pio: hotpath
        now = monotonic_s()
        if now - self._last_sweep < 1.0:
            return
        self._last_sweep = now
        for conn in list(self._conns.values()):
            if now - conn.last > self._idle_timeout_s:
                # idle / slowloris: no bytes for the whole window
                self._close(conn)

    def _accept_ready(self) -> None:  # pio: hotpath
        for _ in range(64):
            try:
                # non-blocking listener: EAGAIN ends the accept burst
                # instead of parking the loop
                # pio: disable=hotpath-blocking
                s, addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            s.setblocking(False)
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(s, addr[0])
            self._conns[s.fileno()] = conn
            conn.mask = selectors.EVENT_READ
            self._sel.register(s, conn.mask, data=conn)
            if self._conn_gauge is not None:
                self._conn_gauge.inc(1.0)

    def _close(self, conn: _Conn) -> None:  # pio: hotpath
        if conn.closed:
            return
        conn.closed = True
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.sock.close()
        except OSError:
            pass
        if self._conn_gauge is not None:
            self._conn_gauge.inc(-1.0)

    def _update_interest(self, conn: _Conn) -> None:  # pio: hotpath
        if conn.closed:
            return
        mask = selectors.EVENT_WRITE if conn.obuf else 0
        if len(conn.obuf) < _HIGH_WATER:
            mask |= selectors.EVENT_READ
        if mask != conn.mask:
            conn.mask = mask
            self._sel.modify(conn.sock, mask or selectors.EVENT_READ,
                             data=conn)

    # -- read side -----------------------------------------------------

    def _on_readable(self, conn: _Conn) -> None:  # pio: hotpath
        while True:
            try:
                # non-blocking socket: EAGAIN ends the drain instead of
                # parking the shared loop
                # pio: disable=hotpath-blocking
                chunk = conn.sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close(conn)
                return
            if not chunk:
                conn.eof = True
                break
            conn.ibuf += chunk
            if len(chunk) < _RECV_CHUNK:
                break
        if conn.ibuf and not conn.close_after:
            conn.last = monotonic_s()
            self._drain_requests(conn)
        if conn.eof and not conn.closed:
            if conn.headers is not None and not conn.close_after:
                # peer half-closed mid-body: same 400 as the threaded
                # front's short read
                self._reject(conn, 400, "incomplete body")
                self._flush(conn)
            self._close(conn)

    def _drain_requests(self, conn: _Conn) -> None:  # pio: hotpath
        """Serve every complete pipelined request in the buffer, in
        batches of ``PIO_TPU_HTTP_MAX_PIPELINE`` between flushes."""
        total = 0
        while not conn.closed:
            served = self._advance(conn)
            total += served
            if served == 0 or conn.obuf or conn.close_after:
                break
        if total > 1 and self._pipelined_total is not None:
            self._pipelined_total.inc(float(total - 1))

    def _advance(self, conn: _Conn) -> int:  # pio: hotpath
        served = 0
        while (not conn.close_after and served < self._max_pipeline
               and len(conn.obuf) < _HIGH_WATER):
            if self._serve_one(conn) is not True:
                break
            served += 1
        self._flush(conn)
        return served

    # -- incremental parser --------------------------------------------

    def _serve_one(self, conn: _Conn):  # pio: hotpath
        """Parse (incrementally) and dispatch ONE request from the
        connection's reuse buffer. Returns True when a request was
        served, None when more bytes are needed, False when the request
        was rejected (connection closing). Status codes and caps mirror
        the threaded parser line by line — tests/test_evfront.py runs
        the same edge-case suite over both fronts."""
        ibuf = conn.ibuf
        if conn.headers is None:
            if conn.hdr_end < 0:
                if conn.scan_pos == 0 and conn.n_lines == 0:
                    # stray CRLFs between requests — tolerated
                    while ibuf[:2] == b"\r\n" or ibuf[:1] == b"\n":
                        del ibuf[:2 if ibuf[:2] == b"\r\n" else 1]
                if not ibuf:
                    return None
                if conn.t_req < 0:
                    # the accept clock starts at the first request byte —
                    # keep-alive idle wait is not request latency
                    conn.t_req = monotonic_s()
                if self._scan_headers(conn) is not True:
                    return None if conn.hdr_end < 0 \
                        and not conn.close_after else False
            if self._parse_header_block(conn) is False:
                return False
            if conn.headers is None:
                return False  # rejected inside the block parse
        need = conn.hdr_end + conn.length
        if len(ibuf) < need:
            return None
        return self._dispatch_one(conn, need)

    def _scan_headers(self, conn: _Conn):  # pio: hotpath
        """Advance the newline scan until the header block's blank line;
        enforces line-length and header-count caps on PARTIAL data, so a
        slowloris feeding one endless header line is rejected long
        before any terminator."""
        ibuf = conn.ibuf
        while True:
            j = ibuf.find(b"\n", conn.scan_pos)
            if j < 0:
                conn.scan_pos = len(ibuf)
                if len(ibuf) - conn.line_start > _MAX_LINE:
                    if conn.n_lines == 0:
                        return self._reject(conn, 400,
                                            "request line too long")
                    return self._reject(conn, 431, "header line too long")
                return None
            if j + 1 - conn.line_start > _MAX_LINE:
                if conn.n_lines == 0:
                    return self._reject(conn, 400, "request line too long")
                return self._reject(conn, 431, "header line too long")
            blank = (j == conn.line_start
                     or (j == conn.line_start + 1
                         and ibuf[conn.line_start] == 0x0D))
            if blank:
                if conn.n_lines == 0:
                    # stray blank before the request line
                    conn.line_start = conn.scan_pos = j + 1
                    continue
                conn.hdr_end = j + 1
                return True
            conn.n_lines += 1
            if conn.n_lines > _MAX_HEADERS:
                return self._reject(conn, 431, "too many headers")
            conn.line_start = conn.scan_pos = j + 1

    def _parse_header_block(self, conn: _Conn):  # pio: hotpath
        """Request line + headers out of ``ibuf[:hdr_end]`` — the same
        checks (and messages) as the threaded parser. The block is
        decoded ONCE (latin-1 is total, so it cannot fail) and split on
        bare ``\\n`` only — per-line ``bytes.decode`` calls dominated
        this function's share of the serial-request profile, and
        ``str.splitlines`` would add Unicode boundaries (NEL et al.)
        that the byte-level scan never treats as line breaks."""
        block = bytes(conn.ibuf[:conn.hdr_end]).decode("latin-1")
        lines = block.split("\n")
        parts = lines[0].strip().split()
        if len(parts) != 3:
            return self._reject(conn, 400, "malformed request line")
        method, target, version = parts
        if not method.isascii():
            # the threaded parser's ascii decode of the method — a
            # latin-1 method byte must stay a 400, not a 405
            return self._reject(conn, 400, "malformed request line")
        if not version.startswith("HTTP/1."):
            return self._reject(conn, 400, "unsupported HTTP version")
        if method not in _http._ALLOWED_METHODS:
            return self._reject(conn, 405, f"method {method!r} not allowed")
        headers: Dict[str, str] = {}
        last = None
        for hline in lines[1:]:
            stripped = hline.strip()
            if not stripped:
                continue
            if hline[0] in " \t":
                # RFC 9112 obs-fold continuation line
                if last is not None:
                    headers[last] += " " + stripped
                continue
            name, sep, value = hline.partition(":")
            if not sep:
                return self._reject(conn, 400, "malformed header")
            last = name.strip().lower()
            val = value.strip()
            if last in ("content-length", "transfer-encoding") \
                    and headers.get(last, val) != val:
                # differing duplicate framing headers are a request-
                # smuggling primitive behind a proxy (RFC 9112 §6.3)
                return self._reject(conn, 400, f"duplicate {last}")
            headers[last] = val
        if headers.get("transfer-encoding"):
            return self._reject(conn, 411, "Content-Length required")
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            return self._reject(conn, 400, "bad Content-Length")
        if length < 0:
            return self._reject(conn, 400, "bad Content-Length")
        if length > _http.MAX_BODY_MB * 2 ** 20:
            return self._reject(
                conn, 413, f"body exceeds {_http.MAX_BODY_MB:g} MiB limit"
            )
        ctype = headers.get("content-type", "").lower()
        packed = ctype.startswith(PACKED_QUERY_CONTENT_TYPE)
        octet = ctype.startswith("application/octet-stream")
        if length and not packed \
                and length > _http.MAX_JSON_BODY_MB * 2 ** 20:
            # structured bodies are parsed in RAM; no large_uploads mode
            # on this front, so octet-stream gets the same tight cap
            return self._reject(
                conn, 413,
                f"body exceeds {_http.MAX_JSON_BODY_MB:g} MiB limit "
                f"for {ctype or 'structured'} content",
            )
        if length and packed and length > _http.MAX_JSON_BODY_MB * 2 ** 20:
            return self._reject(
                conn, 413,
                f"body exceeds {_http.MAX_JSON_BODY_MB:g} MiB limit "
                f"for {ctype} content",
            )
        conn.http10 = version == "HTTP/1.0"
        if self._pre_body is not None and length:
            # auth before the body is DISPATCHED (kernel delivery can't
            # be prevented on a shared loop, but no handler sees it)
            try:
                self._pre_body(Request(
                    method=method, path=target.partition("?")[0],
                    params={}, body=None, headers=headers,
                    client_addr=conn.peer,
                ))
            except HTTPError as e:
                return self._reject(conn, e.status, e.message)
            except Exception:
                log.exception("pre_body hook failed")
                return self._reject(conn, 500, "internal server error")
        if length and headers.get(
            "expect", ""
        ).lower().startswith("100-continue"):
            # invite the body only after the caps + pre-body auth passed
            conn.obuf += b"HTTP/1.1 100 Continue\r\n\r\n"
        conn.method = method
        conn.target = target
        conn.headers = headers
        conn.length = length
        conn.body_packed = bool(packed and length)
        conn.body_octet = bool(octet and length)
        return True

    def _dispatch_one(self, conn: _Conn, need: int):  # pio: hotpath
        """Body complete: build the Request, run the handler inline,
        queue the response, reclaim the consumed buffer prefix."""
        ibuf = conn.ibuf
        method, target, headers = conn.method, conn.target, conn.headers
        hdr_end, length = conn.hdr_end, conn.length
        conn_tok = headers.get("connection", "").lower()
        http10 = conn.http10
        if http10:
            close = "keep-alive" not in conn_tok
        else:
            close = "close" in conn_tok
        if close:
            conn.close_after = True
        head_only = method == "HEAD"
        path, _, query = target.partition("?")
        params = (
            {k: v[0] for k, v in parse_qs(query).items()} if query else {}
        )
        base_mv = None
        body = None
        raw = b""
        body_file = None
        packed = None
        if conn.body_packed:
            # zero-copy fast path: the handler gets a view into ibuf —
            # valid only for the (synchronous) handler call, after which
            # the buffer prefix is reclaimed below
            base_mv = memoryview(ibuf)
            packed = _packed_view(base_mv, hdr_end, need)
        elif length:
            raw = bytes(ibuf[hdr_end:need])
            if conn.body_octet:
                # no spooling on this front: within-cap octet bodies are
                # handed over as an in-memory file (blob-scale uploads
                # belong on the threaded front)
                body_file = io.BytesIO(raw)
                raw = b""
            else:
                try:
                    body = json.loads(raw.decode("utf-8"))
                except (UnicodeDecodeError, json.JSONDecodeError):
                    body = raw.decode("utf-8", errors="replace")
        req = Request(
            method=method, path=path, params=params, body=body,
            raw_body=raw, body_file=body_file, headers=headers,
            client_addr=conn.peer, packed=packed,
        )
        req.read_s = monotonic_s() - conn.t_req
        try:
            status, out = self._router.dispatch(req)
        except HTTPError as e:
            status = e.status
            out = (
                json_response({"message": e.message}, e.headers)
                if e.headers else {"message": e.message}
            )
        except Exception:
            log.exception("unhandled error on %s %s", method, path)
            status, out = 500, {"message": "internal server error"}
        finally:
            if body_file is not None:
                body_file.close()
        t_write = monotonic_s()
        self._respond(conn, status, out, head_only, http10, req, t_write)
        if base_mv is not None:
            try:
                packed.release()
                base_mv.release()
            except BufferError:
                # a handler leaked a reference to the view; fall back to
                # copying the tail out instead of compacting in place
                conn.ibuf = bytearray(ibuf[need:])
                conn.reset_parse()
                return True
        del ibuf[:need]
        conn.reset_parse()
        return True

    # -- write side ----------------------------------------------------

    def _head_prefix(self, status: int) -> bytes:  # pio: hotpath
        got = self._static_head.get(status)
        if got is None:
            got = (
                f"HTTP/1.1 {status} {_http._REASONS.get(status, '')}\r\n"
                f"Server: {self._name}\r\n"
            ).encode("latin-1")
            self._static_head[status] = got
        return got

    def _respond(self, conn, status, body, head_only, http10, req, t_write):  # pio: hotpath
        """Serialize one response into the PER-CONNECTION write buffer
        and queue the post-write hooks at its absolute end offset."""
        extra: Any = ()
        if isinstance(body, FileResponse):
            try:
                # local file read for Router parity (status pages); the
                # blob daemon's multi-GB streams stay on the threaded
                # front, so this is small and bounded
                # pio: disable=hotpath-blocking
                f = open(body.path, "rb")
            except OSError:
                self._respond(conn, 404, {"message": "no such blob"},
                              head_only, http10, req, t_write)
                return
            with f:
                payload = f.read()
            ctype = body.content_type
        elif isinstance(body, RawResponse):
            payload = (
                body.body if isinstance(body.body, bytes)
                else body.body.encode()
            )
            ctype = body.content_type
            extra = body.headers.items()
        else:
            try:
                payload = (
                    json.dumps(body).encode() if body is not None else b""
                )
            except (TypeError, ValueError):
                log.exception("response not JSON-serializable")
                status = 500
                payload = b'{"message": "response not JSON-serializable"}'
            ctype = "application/json; charset=UTF-8"
        obuf = conn.obuf
        obuf += self._head_prefix(status)
        obuf += _http._http_date_line()
        obuf += _http._ctype_line(ctype)
        obuf += b"Content-Length: %d\r\n" % len(payload)
        for k, v in extra:
            obuf += f"{k}: {v}\r\n".encode("latin-1")
        if conn.close_after:
            obuf += b"Connection: close\r\n"
        elif http10:
            obuf += b"Connection: keep-alive\r\n"
        obuf += b"\r\n"
        if payload and not head_only:
            obuf += payload
        if req is not None and (req.on_written is not None
                                or req.after_response is not None):
            conn.cbs.append((conn.sent_abs + len(obuf), req.on_written,
                             t_write, req.after_response))

    def _reject(self, conn: _Conn, status: int, message: str):  # pio: hotpath
        """Terminal error response: mirror of the threaded front's
        ``_reject`` — answer, then close once the bytes drain."""
        conn.close_after = True
        self._respond(conn, status, {"message": message},
                      False, conn.http10, None, 0.0)
        return False

    def _on_writable(self, conn: _Conn) -> None:  # pio: hotpath
        self._flush(conn)
        if not conn.closed and not conn.obuf and conn.ibuf \
                and not conn.close_after:
            # backpressure released: serve what accumulated while the
            # peer was slow to read
            self._drain_requests(conn)

    def _flush(self, conn: _Conn) -> None:  # pio: hotpath
        if conn.closed:
            return
        obuf = conn.obuf
        while obuf:
            try:
                # non-blocking send(): takes what fits in the kernel
                # buffer, EAGAIN re-arms EVENT_WRITE instead of parking
                n = conn.sock.send(obuf)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close(conn)
                return
            if n <= 0:
                break
            conn.sent_abs += n
            del obuf[:n]
        self._fire_written(conn)
        if not obuf and conn.close_after:
            self._close(conn)
            return
        self._update_interest(conn)

    def _fire_written(self, conn: _Conn) -> None:  # pio: hotpath
        cbs = conn.cbs
        while cbs and cbs[0][0] <= conn.sent_abs:
            _, on_written, t_write, after = cbs.popleft()
            if on_written is not None:
                try:
                    on_written(monotonic_s() - t_write)
                except Exception:
                    log.exception("on_written hook failed")
            if after is not None:
                try:
                    after()
                except Exception:
                    log.exception("after_response hook failed")
