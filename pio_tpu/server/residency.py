"""Device-resident serving scorers with donated dispatch buffers.

The warmed serving path (bucket cache + micro-batcher + batch lane,
ISSUE 7) still pays two host-side taxes per dispatch: the model's
parameters are re-fed from the host mirror into every ``predict`` call,
and the query features cross the link as float32. This module makes the
hot path device-resident and (near-)zero-copy:

* **Resident params** — a :class:`ResidentLinearScorer` places the
  template's serving parameters on the device ONCE at deploy/hot-swap
  (``jax.device_put`` behind the query server's swap lock) as jax
  arrays; every dispatch passes the same device buffers to a shared
  jitted program instead of re-uploading a host mirror. Hot-swap
  :meth:`retire`\\ s the old generation — a retired scorer refuses to
  serve, so stale weights can never answer a live query.

* **Donated output buffers** — the jitted scorer takes a pre-allocated
  per-bucket logits buffer with ``donate_argnums=(0,)`` and returns the
  refreshed buffer: steady state ping-pongs ONE device allocation per
  bucket instead of alloc/free per call. The buffer rides inside a
  :class:`DonatedBuffer` guard — a donated buffer must never be re-read
  (on backends that honor donation the memory now holds the new logits)
  and the guard makes a re-read raise instead of returning garbage.
  Donation accounting: a dispatch that recycled an existing bucket
  buffer is a **hit**; a cold shape that had to allocate fresh is a
  **miss** (first dispatch per bucket per generation — flat in steady
  state). Backends that additionally reclaim the donated input's memory
  (TPU/GPU; CPU ignores donation) are counted as ``backend_reclaims``.

* **int8 feature wire** — with ``wire="int8"`` the query features are
  quantized at request decode with the TRAINING-side per-column scales
  (``x_q = clip(rint(x / s), -127, 127)``) and the scales fold into the
  resident weights (``X @ W = X_q @ (s ⊙ W)`` — the identity the
  training wire already uses, see ``models/logreg.py``), so per-request
  H2D drops to one byte per feature and the device math is unchanged.

Env knobs (see docs/operations.md):

* ``PIO_TPU_DEVICE_RESIDENT`` — ``1`` force-on, ``0`` force-off,
  unset/``auto``: resident only on a real accelerator backend (CPU
  serving keeps the host-numpy path that every existing deploy runs).
* ``PIO_TPU_SERVE_WIRE`` — ``int8`` / ``float32`` / unset ``auto``
  (int8 whenever the model carries training scales, else float32).
"""

from __future__ import annotations

import functools
import logging
import os
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from pio_tpu.utils import knobs
from pio_tpu.faults import failpoint
from pio_tpu.obs import devicewatch

log = logging.getLogger("pio_tpu.residency")

WIRE_INT8 = "int8"
WIRE_FLOAT32 = "float32"


def enabled() -> bool:
    """Is device-resident serving on for this process?

    ``PIO_TPU_DEVICE_RESIDENT=1`` forces on (tests, CPU smoke),
    ``=0`` forces off; the ``auto`` default enables residency only on a
    real accelerator backend — on CPU the host-numpy predict path is
    already resident by definition and existing deploys keep it."""
    flag = knobs.knob_str("PIO_TPU_DEVICE_RESIDENT").strip().lower()
    if flag in ("0", "off", "false"):
        return False
    if flag in ("1", "on", "true"):
        return True
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:
        return False


def wire_mode(has_scales: bool) -> str:
    """Resolve the serving feature wire: the ``PIO_TPU_SERVE_WIRE``
    override, else int8 whenever training scales exist to fold."""
    raw = knobs.knob_str("PIO_TPU_SERVE_WIRE").strip().lower()
    if raw == WIRE_INT8:
        return WIRE_INT8 if has_scales else WIRE_FLOAT32
    if raw == WIRE_FLOAT32:
        return WIRE_FLOAT32
    return WIRE_INT8 if has_scales else WIRE_FLOAT32


class DonatedBuffer:
    """Single-use handle around a device buffer headed into a
    ``donate_argnums`` call.

    Donation transfers ownership of the buffer's memory to the compiled
    program — after the call the old array may alias the OUTPUT, so any
    further read through the old reference is a correctness bug (jax
    only faults on backends that honor donation; CPU silently returns
    stale bytes). The guard makes the contract enforceable everywhere:
    :meth:`take` hands the raw buffer out exactly once, and every later
    ``take``/``array`` raises loudly."""

    __slots__ = ("_buf", "_taken")

    def __init__(self, buf):
        self._buf = buf
        self._taken = False

    def take(self):
        """Hand the raw device buffer to the donating call. One shot."""
        if self._taken:
            raise RuntimeError(
                "donated device buffer re-used: this buffer was already "
                "handed to a donate_argnums dispatch and its memory may "
                "now hold that dispatch's output"
            )
        self._taken = True
        buf, self._buf = self._buf, None
        return buf

    def array(self) -> np.ndarray:
        """Host copy of the buffer — raises once donated."""
        if self._taken or self._buf is None:
            raise RuntimeError(
                "donated device buffer re-read after donation"
            )
        return np.asarray(self._buf)

    @property
    def donated(self) -> bool:
        return self._taken


@functools.lru_cache(maxsize=1)
def _scorer_fn():
    """The ONE jitted linear scorer shared by every resident model and
    bucket: params and the donated logits buffer are arguments, so jax's
    shape-keyed dispatch cache gives each (bucket, D, C, wire-dtype)
    combination its own executable under a single wrapper — hot-swap
    generations and multiple engines reuse compiles, and the warmup
    sweep in the query server is what populates the cache."""
    import jax
    import jax.numpy as jnp

    # keep_unused: the donated buffer contributes MEMORY, not values —
    # without it jit would DCE the argument and the input/output alias
    # match (same [B, C] f32 aval as the returned logits) never forms
    @functools.partial(jax.jit, donate_argnums=(0,), keep_unused=True)
    def score(logits_buf, x, w, b):
        # int8 codes (or raw f32 features) against the resident weights;
        # the scales are pre-folded into w, so both wires share one
        # program shape-for-shape. logits has logits_buf's aval exactly,
        # which is what lets XLA alias the donated buffer's memory.
        logits = (
            jnp.dot(x.astype(jnp.float32), w,
                    preferred_element_type=jnp.float32)
            + b
        )
        del logits_buf  # consumed via donation (memory, not values)
        codes = jnp.argmax(logits, axis=1).astype(jnp.int32)
        return logits, codes

    return score


class ResidentLinearScorer:
    """Device-resident ``argmax(X @ W + b)`` scorer for the linear
    classifier templates (logreg weights, multinomial-NB log-thetas).

    Built by ``Algorithm.resident_scorer`` at deploy/hot-swap; the query
    server places it before the swap is visible, binds the metric sinks,
    and retires the previous generation when the swap lands.
    """

    def __init__(
        self,
        weights: np.ndarray,
        bias: np.ndarray,
        scales: Optional[np.ndarray] = None,
        name: str = "",
        query_factory: Optional[Callable[[np.ndarray], object]] = None,
        result_factory: Optional[Callable[[int], object]] = None,
        mesh=None,
    ):
        import jax
        import jax.numpy as jnp

        W = np.asarray(weights, np.float32)  # [D, C]
        b = np.asarray(bias, np.float32)  # [C]
        if W.ndim != 2 or b.shape != (W.shape[1],):
            raise ValueError(
                f"weights [D,C] / bias [C] expected, got {W.shape} {b.shape}"
            )
        self.name = name
        self.in_dim = int(W.shape[0])
        self.n_classes = int(W.shape[1])
        self.scales = (
            np.asarray(scales, np.float32).reshape(self.in_dim)
            if scales is not None else None
        )
        self.wire = wire_mode(self.scales is not None)
        #: mints the template's Query from a dequantized feature row —
        #: lets the lane drainer turn a packed int8 payload back into a
        #: servable query (see batchlane.PackedQuery)
        self.query_factory = query_factory
        #: maps one argmax class code straight to the template's result
        #: object. Attaching it is the template's declaration that a
        #: wire-codes dispatch is result-equivalent to its full
        #: supplement → predict path, which lets the packed query wire
        #: skip the dequantize → Query → re-quantize round trip
        self.result_factory = result_factory
        if self.wire == WIRE_INT8:
            # fold the training scales into the resident weights once:
            # X @ W == (X/s·s) @ W == X_q @ (s ⊙ W) up to quantization
            w_eff = self.scales[:, None] * W
        else:
            w_eff = W
        # the one-time placement: these device arrays ARE the serving
        # params for this generation; no per-dispatch host re-feed.
        # With a multi-chip mesh the weights row-shard on the contraction
        # dim (each chip holds D/n rows; the jitted matmul closes with a
        # psum and the logits come back replicated, so the donated
        # buffers keep their single-buffer aval and aliasing). A D that
        # doesn't divide the axis falls back to replicated placement
        # (``mesh_fallback`` — the service counts it).
        self._mesh = None
        self._x_sharding = None
        self.mesh_fallback = False
        if mesh is not None and int(np.prod(mesh.devices.shape)) > 1:
            from pio_tpu.parallel.compat import NamedSharding
            from pio_tpu.parallel.compat import PartitionSpec as P
            from pio_tpu.parallel.partition import assert_device_budget

            axis = (
                "data" if "data" in mesh.axis_names else mesh.axis_names[0]
            )
            if self.in_dim % int(mesh.shape[axis]) == 0:
                n_dev = int(np.prod(mesh.devices.shape))
                assert_device_budget(
                    w_eff.nbytes + b.nbytes, n_dev,
                    f"resident scorer {name!r} mesh placement",
                )
                self._mesh = mesh
                self._w_dev = jax.device_put(
                    jnp.asarray(w_eff), NamedSharding(mesh, P(axis, None))
                )
                self._x_sharding = NamedSharding(mesh, P())
                self._b_dev = jax.device_put(
                    jnp.asarray(b), self._x_sharding
                )
            else:
                self.mesh_fallback = True
        if self._mesh is None:
            from pio_tpu.parallel.partition import assert_device_budget

            assert_device_budget(
                w_eff.nbytes + b.nbytes, 1,
                f"resident scorer {name!r} placement",
            )
            self._w_dev = jax.device_put(jnp.asarray(w_eff))
            self._b_dev = jax.device_put(jnp.asarray(b))
        self.placed_bytes = int(w_eff.nbytes + b.nbytes)
        #: per-bucket donated logits buffers, keyed by batch size; the
        #: value cycles: donated into the dispatch, replaced by the
        #: returned (aliased) buffer
        self._out_bufs: Dict[int, DonatedBuffer] = {}
        self._lock = threading.Lock()
        self.retired = False
        # accounting (host ints; the service mirrors them into counters
        # via the bound sinks)
        self.h2d_bytes = 0
        self.dispatches = 0
        self.donation_hits = 0
        self.donation_misses = 0
        self.backend_reclaims = 0
        self._on_h2d: Optional[Callable[[int], None]] = None
        self._on_donation: Optional[Callable[[str], None]] = None
        # device ledger (ISSUE 17): book the placement with the active
        # watch; retire() releases it. Per-scorer compile attribution
        # keys off the bucket sizes this instance has dispatched.
        self._dw_key = f"{name}#{id(self):x}"
        devicewatch.ledger_place(
            "resident", self._dw_key, self.placed_bytes, name=name
        )

    # -- service wiring ----------------------------------------------------
    def bind(self, on_h2d=None, on_donation=None) -> "ResidentLinearScorer":
        """Attach the query server's metric sinks (h2d bytes counter,
        donation outcome counter)."""
        self._on_h2d = on_h2d
        self._on_donation = on_donation
        return self

    def prealloc(self, buckets) -> None:
        """Pre-allocate the per-bucket output buffers for the serving
        ladder so even each bucket's FIRST hot dispatch recycles instead
        of allocating (the warmup sweep then compiles against the same
        buffers)."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            for b in buckets:
                if b not in self._out_bufs:
                    z = jnp.zeros((int(b), self.n_classes), jnp.float32)
                    self._out_bufs[b] = DonatedBuffer(
                        jax.device_put(z, self._x_sharding)
                        if self._x_sharding is not None
                        else jax.device_put(z)
                    )
            donated = sum(
                int(b) * self.n_classes * 4 for b in self._out_bufs
            )
        devicewatch.ledger_place(
            "donated", self._dw_key, donated,
            name=f"{self.name} logits buffers",
        )

    def retire(self) -> None:
        """Hot-swap eviction: drop the device params and refuse further
        dispatches. The old generation's buffers free with the refs."""
        with self._lock:
            self.retired = True
            self._w_dev = None
            self._b_dev = None
            self._out_bufs.clear()
        devicewatch.ledger_release("resident", self._dw_key)
        devicewatch.ledger_release("donated", self._dw_key)

    # -- wire encode -------------------------------------------------------
    def quantize(self, X: np.ndarray) -> np.ndarray:
        """Host-side int8 wire encode of raw float features with the
        training scales (exact inverse of the fold in the weights)."""
        if self.scales is None:
            raise ValueError(f"scorer {self.name!r} has no feature scales")
        return np.clip(
            np.rint(np.asarray(X, np.float32) / self.scales), -127, 127
        ).astype(np.int8)

    def dequantize(self, codes: np.ndarray) -> np.ndarray:
        """int8 wire codes back to (approximate) float features —
        re-quantizing the result yields the identical codes, which is
        what makes the packed lane path round-trip exactly."""
        if self.scales is None:
            raise ValueError(f"scorer {self.name!r} has no feature scales")
        return codes.astype(np.float32) * self.scales

    def encode(self, X: np.ndarray) -> np.ndarray:
        """Features → wire form (int8 codes or float32 passthrough)."""
        if self.wire == WIRE_INT8:
            return self.quantize(X)
        return np.ascontiguousarray(X, np.float32)

    # -- dispatch ----------------------------------------------------------
    def score_codes(self, X: np.ndarray) -> np.ndarray:
        """Argmax class codes for a [B, D] float feature batch through
        the resident params (wire encode on host, one h2d, one compiled
        dispatch)."""
        return self.score_wire(self.encode(X))

    def score_wire(self, wire: np.ndarray) -> np.ndarray:
        """Dispatch an already wire-encoded [B, D] batch (the packed
        lane path lands here without re-quantizing)."""
        import jax

        if self.retired:
            raise RuntimeError(
                f"resident scorer {self.name!r} is retired (model was "
                f"hot-swapped); refusing to serve stale weights"
            )
        if wire.ndim != 2 or wire.shape[1] != self.in_dim:
            raise ValueError(
                f"wire batch [B,{self.in_dim}] expected, got {wire.shape}"
            )
        n = wire.shape[0]
        failpoint("scorer.h2d.ship")
        if self._x_sharding is not None:
            x_dev = jax.device_put(wire, self._x_sharding)
        else:
            # let the jitted call ship the host array itself: the
            # runtime's C++ transfer path is several times cheaper than
            # an explicit device_put for the per-request single-query
            # dispatch (the bytes crossing host→device are identical)
            x_dev = np.ascontiguousarray(wire)
        nbytes = int(wire.nbytes)
        self.h2d_bytes += nbytes
        if self._on_h2d is not None:
            self._on_h2d(nbytes)
        # per-bucket donated buffer: recycle the standing allocation
        # (hit) or mint one for a cold shape (miss — once per bucket per
        # generation; the prealloc'd ladder never misses)
        failpoint("scorer.donate.dispatch")
        with self._lock:
            if self.retired:
                raise RuntimeError(
                    f"resident scorer {self.name!r} retired mid-dispatch"
                )
            guard = self._out_bufs.pop(n, None)
        outcome = "hit" if guard is not None else "miss"
        if guard is None:
            import jax.numpy as jnp

            z = jnp.zeros((n, self.n_classes), jnp.float32)
            guard = DonatedBuffer(
                jax.device_put(z, self._x_sharding)
                if self._x_sharding is not None
                else jax.device_put(z)
            )
        raw = guard.take()
        # compile attribution: the first dispatch at this program shape
        # (batch n × this model's dims) is the trace+compile entry.
        # Keyed on the WATCH, not the scorer instance: _scorer_fn's jit
        # cache is process-global, so a hot-swapped replacement scorer
        # re-dispatching a warmed shape compiles nothing and must not
        # be recounted. Steady buckets add one set-membership test to
        # the hot path, nothing more.
        with devicewatch.compile_span(
            "resident_scorer", key=(n, self.in_dim, self.n_classes)
        ):
            new_logits, codes = _scorer_fn()(
                raw, x_dev, self._w_dev, self._b_dev
            )
        # the old buffer object is dead either way; count the backends
        # that actually reclaimed its memory (CPU ignores donation)
        try:
            if raw.is_deleted():
                self.backend_reclaims += 1
        except AttributeError:
            pass
        with self._lock:
            if not self.retired:
                self._out_bufs[n] = DonatedBuffer(new_logits)
        self.dispatches += 1
        if outcome == "hit":
            self.donation_hits += 1
        else:
            self.donation_misses += 1
        if self._on_donation is not None:
            self._on_donation(outcome)
        return np.asarray(codes)

    # -- introspection -----------------------------------------------------
    # pio: endpoint=/stats.json
    def to_dict(self) -> dict:
        total = self.donation_hits + self.donation_misses
        return {
            "name": self.name,
            "wire": self.wire,
            "inDim": self.in_dim,
            "nClasses": self.n_classes,
            "paramBytes": self.placed_bytes,
            "sharded": self._mesh is not None,
            "retired": self.retired,
            "dispatches": self.dispatches,
            "h2dBytes": self.h2d_bytes,
            "donation": {
                "hits": self.donation_hits,
                "misses": self.donation_misses,
                "hitRate": (
                    round(self.donation_hits / total, 4) if total else None
                ),
                "backendReclaims": self.backend_reclaims,
            },
        }
