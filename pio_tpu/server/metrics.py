"""Prometheus text-exposition helpers for the HTTP servers.

Since ISSUE 1 the real machinery lives in :mod:`pio_tpu.obs` — typed
Counter/Gauge/Histogram families with ``# HELP``/``# TYPE`` exposition,
per-stage histograms and pool-wide shared-memory aggregation. This
module remains as the thin HTTP-facing shim: ``render`` wraps exposition
lines in the proper scrape content type, and ``escape_label`` stays as a
compatibility wrapper over the obs escaping helpers (existing plugins
import it from here).
"""

from __future__ import annotations

from pio_tpu.obs.metrics import escape_help, escape_label_value

#: Prometheus scrape content type (text format 0.0.4).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_label(value: str) -> str:
    """Escape a label value per the Prometheus text format
    (compatibility wrapper over :func:`pio_tpu.obs.escape_label_value`)."""
    return escape_label_value(value)


def render(lines: list) -> "object":
    """Wrap exposition lines (a list — the one shape every metric surface
    uses) in the proper content type."""
    from pio_tpu.server.http import RawResponse

    return RawResponse("\n".join(lines) + "\n", content_type=CONTENT_TYPE)


__all__ = ["CONTENT_TYPE", "escape_help", "escape_label", "render"]
