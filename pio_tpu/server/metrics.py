"""DEPRECATED compatibility shim — import from ``pio_tpu.obs`` (escaping
helpers) and ``pio_tpu.server.http`` (``metrics_response``) instead.

Everything this module once provided has a real home now: the metric
types and escaping live in :mod:`pio_tpu.obs.metrics`, and the HTTP
scrape wrapper is :func:`pio_tpu.server.http.metrics_response`. The last
in-tree callers have been rerouted; this shim remains one release for
out-of-tree plugins that ``from pio_tpu.server.metrics import
escape_label`` and will be deleted in a later PR.
"""

from __future__ import annotations

import warnings

from pio_tpu.obs.metrics import escape_help, escape_label_value
from pio_tpu.server.http import METRICS_CONTENT_TYPE as CONTENT_TYPE
from pio_tpu.server.http import metrics_response

warnings.warn(
    "pio_tpu.server.metrics is deprecated: import escaping helpers from "
    "pio_tpu.obs and metrics_response from pio_tpu.server.http",
    DeprecationWarning,
    stacklevel=2,
)


def escape_label(value: str) -> str:
    """Escape a label value per the Prometheus text format
    (compatibility wrapper over :func:`pio_tpu.obs.escape_label_value`)."""
    return escape_label_value(value)


def render(lines: list) -> "object":
    """Compatibility wrapper over
    :func:`pio_tpu.server.http.metrics_response`."""
    return metrics_response(lines)


__all__ = ["CONTENT_TYPE", "escape_help", "escape_label", "render"]
