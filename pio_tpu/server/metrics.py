"""Prometheus text-exposition helpers for the HTTP servers.

The reference exposes operational state as JSON only (`/stats.json` on the
Event and Query servers — `data/api/Stats.scala`, `CreateServer.scala`,
UNVERIFIED paths; SURVEY.md §5 observability row). This module adds the
de-facto standard scrape format on top — ``GET /metrics`` on both servers —
so the rebuild drops into Prometheus/Grafana stacks without an exporter
sidecar. Counters only (no client library dependency); the text format is
simple enough to emit directly.
"""

from __future__ import annotations


def escape_label(value: str) -> str:
    """Escape a label value per the Prometheus text format."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def render(lines: list) -> "object":
    """Wrap exposition lines (a list — the one shape every metric surface
    uses) in the proper content type."""
    from pio_tpu.server.http import RawResponse

    return RawResponse(
        "\n".join(lines) + "\n",
        content_type="text/plain; version=0.0.4; charset=utf-8",
    )
