"""Event Server — REST ingestion daemon (:7070 by default).

Rebuild of the reference's ``data/.../data/api/EventServer.scala``
(UNVERIFIED path; see SURVEY.md). Routes:

    GET    /                          alive check
    POST   /events.json               ingest one event (201 + eventId)
    GET    /events.json               filtered query (reversed by default)
    GET    /events/<id>.json          fetch one
    DELETE /events/<id>.json          delete one
    POST   /batch/events.json         ≤50 events, per-item statuses
    GET    /stats.json                per-app counters since start
    POST   /webhooks/<name>.json      JSON webhook connector
    POST   /webhooks/<name>.form      form webhook connector

Auth: ``accessKey`` query param (or ``Authorization`` header); the key maps
to an app and an optional event-name whitelist. ``channel`` selects a named
sub-stream (must exist; 400 otherwise).
"""

from __future__ import annotations

import datetime as _dt
import logging
import threading
from contextlib import nullcontext
from typing import Any, Callable, Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.analysis.runtime import make_lock
from pio_tpu.data.event import Event, EventValidationError
from pio_tpu.obs import (
    HealthMonitor, MetricsRegistry, RequestWindow, TRACE_HEADER, Tracer,
    hotpath_payload, monotonic_s, parse_trace_header,
)
from pio_tpu.obs import slog
from pio_tpu.obs.slo import engine_for_specs
from pio_tpu.qos import (
    PRIORITY_HEADER, QoSGate, resolve_policy, retry_after_header,
)
from pio_tpu.server.http import (
    HTTPError, JsonHTTPServer, Request, Router, float_param, int_param,
    json_response, metrics_response,
)
from pio_tpu.server.webhooks import (
    FORM_CONNECTORS,
    JSON_CONNECTORS,
    ConnectorError,
    parse_form,
)
from pio_tpu.storage import Storage

log = logging.getLogger("pio_tpu.eventserver")

MAX_BATCH = 50

#: ingest-path plugin hooks (reference EventServerPlugin): callables
#: (app_id, channel_id, event_dict) -> None, may raise HTTPError to block.
INPUT_BLOCKERS: List[Callable] = []
INPUT_SNIFFERS: List[Callable] = []

#: ingest-path trace stages, in request order: socket read + body parse
#: (HTTP layer), QoS admission + auth, JSON → Event binding, whitelist +
#: input blockers, storage insert/group-commit, response write. Top-level
#: stages TILE the request (their durations sum to the end-to-end time);
#: /debug/hotpath.json budgets against exactly that.
EVENT_STAGES = ("accept", "admit", "parse", "validate", "store", "write")

#: dotted substages attribute time WITHIN the store stage: queueing
#: behind another leader's group-commit flush, and the flush that
#: carried this event (both measured submitter-side in groupcommit).
EVENT_SUBSTAGES = ("store.commit_wait", "store.flush")


def _ms(v):
    """Seconds → rounded milliseconds (None passes through)."""
    return round(v * 1e3, 3) if v is not None else None


class _Stats:
    """Rolling per-app counters (reference ``Stats``/``StatsActor``),
    optionally mirrored into an obs Counter so ``/metrics`` exposition
    and the JSON stats can never disagree."""

    def __init__(self, counter=None):
        self._lock = make_lock("event.stats")
        self._counter = counter
        self.start_time = _dt.datetime.now(_dt.timezone.utc)
        # (app_id, event, entity_type, status) -> count
        self.counts: Dict[Tuple[int, str, str, int], int] = {}

    def tick(self, app_id: int, event: str, entity_type: str, status: int):
        with self._lock:
            key = (app_id, event, entity_type, status)
            self.counts[key] = self.counts.get(key, 0) + 1
        if self._counter is not None:
            self._counter.inc(
                app_id=str(app_id), event=event,
                entity_type=entity_type, status=str(status),
            )

    def to_dict(self) -> dict:
        with self._lock:
            by_app: Dict[int, list] = {}
            for (app_id, event, etype, status), n in sorted(self.counts.items()):
                by_app.setdefault(app_id, []).append(
                    {
                        "event": event,
                        "entityType": etype,
                        "status": status,
                        "count": n,
                    }
                )
        return {
            "startTime": self.start_time.isoformat(),
            "apps": [
                {"appId": app_id, "counts": counts}
                for app_id, counts in by_app.items()
            ],
        }


def _parse_limit(params) -> Optional[int]:
    """Shared ``limit`` query-param contract for the read routes:
    default 20 (the reference default), ``-1`` = explicit no-limit,
    anything below -1 or non-integer → 400."""
    if "limit" not in params:
        return 20
    try:
        limit = int(params["limit"])
    except ValueError:
        raise HTTPError(400, f"invalid limit {params['limit']!r}")
    if limit < -1:
        raise HTTPError(400, "limit must be >= -1")
    return None if limit == -1 else limit


class EventServerService:
    """Route handlers, separable from the HTTP loop for direct testing."""

    #: positive access-key lookups are cached this long — the per-request
    #: metadata SELECT was a measurable slice of single-event ingest cost.
    #: Bounds key-revocation latency to the TTL (misses are never cached,
    #: so a fresh key works immediately).
    AUTH_CACHE_TTL_S = 2.0

    def __init__(self, slos: Optional[List[str]] = None,
                 qos: Optional[Any] = None):
        #: per-instance registry — see query_server (test servers must
        #: not cross-pollinate scrapes through a process global)
        self.obs = MetricsRegistry()
        self._events_counter = self.obs.counter(
            "pio_tpu_events_ingested_total",
            "Events by app/event/status",
            ("app_id", "event", "entity_type", "status"),
        )
        #: full-request latency of the ingest write paths — the latency
        #: SLO source (see query_server's pio_tpu_request_seconds)
        self._request_hist = self.obs.histogram(
            "pio_tpu_request_seconds",
            "Full-request wall seconds of the event write paths",
            ("engine_id",),
        )
        self._request_cell = self._request_hist.labels("eventserver")
        #: end-to-end latency (accept→write, from the post-write hook) —
        #: the denominator of the /debug/hotpath.json attribution budget
        self._e2e_hist = self.obs.histogram(
            "pio_tpu_e2e_seconds",
            "End-to-end wall seconds of the event write paths (socket "
            "read through response write)",
            ("engine_id",),
        )
        self._e2e_cell = self._e2e_hist.labels("eventserver")
        self.tracer = Tracer(
            "event", registry=self.obs,
            stages=EVENT_STAGES + EVENT_SUBSTAGES,
        )
        # tail-based slow-trace capture (see query_server's twin)
        self.tracer.slow_threshold_fn = self._slow_threshold_s
        self.req_window = RequestWindow()
        self.stats = _Stats(counter=self._events_counter)
        slog.install()
        self.obs.add_collector(slog.exposition_lines)
        from pio_tpu import faults as _faults

        self.obs.add_collector(_faults.exposition_lines)
        from pio_tpu.obs import REGISTRY as _global_registry

        # the partitioned log + its replication links meter on the
        # process-global registry (the storage layer has no server
        # instance of its own); bridge that slice into this scrape so
        # the failover drill can watch partition appends and follower
        # acks from the outside
        self.obs.add_collector(
            lambda: _global_registry.render_prefixed(
                ("pio_tpu_partlog_", "pio_tpu_repl_")
            )
        )
        # -- health probes (ISSUE 2) --
        self.health = HealthMonitor()
        self.health.add_liveness("group_commit", self._check_group_commit)
        self.health.add_readiness("storage", self._check_storage_ready)
        # -- SLO engine (optional; specs from the caller or PIO_TPU_SLO) --
        if slos is None:
            env_slos = knobs.knob_str("PIO_TPU_SLO")
            slos = [s for s in env_slos.split(",") if s.strip()]
        self.slo = None
        if slos:
            self.slo = engine_for_specs(
                slos, self.obs,
                availability_source=self._availability_good_total,
                latency_cell_getter=lambda: self._request_cell,
            )
        # -- QoS (ISSUE 3): engine-wide + per-access-key token buckets on
        # the write paths, breaker around storage inserts. The event
        # server never runs in SO_REUSEPORT pool mode, so its buckets
        # are process-local by construction.
        policy = resolve_policy(qos)
        self.qos = (
            QoSGate(policy, self.obs, scope="eventserver")
            if policy is not None else None
        )
        self._storage_breaker = (
            self.qos.breaker("storage") if self.qos is not None else None
        )
        self._auth_cache: dict = {}
        self._auth_gen = 0  # bumped by invalidation; fences re-caching
        self._auth_cache_lock = make_lock("event.auth_cache")
        # a Storage.reset() within AUTH_CACHE_TTL_S must not keep serving
        # AccessKey records from the store that was just dropped
        Storage.add_reset_hook(self.invalidate_auth_cache)
        self.router = Router()
        r = self.router
        r.add("GET", "/", self.alive)
        r.add("POST", "/events\\.json", self.create_event)
        r.add("GET", "/events\\.json", self.find_events)
        r.add("GET", "/events/search\\.json", self.search_events)
        r.add("GET", "/events/([^/]+)\\.json", self.get_event)
        r.add("DELETE", "/events/([^/]+)\\.json", self.delete_event)
        r.add("POST", "/batch/events\\.json", self.batch_events)
        r.add("GET", "/stats\\.json", self.get_stats)
        r.add("GET", "/metrics", self.get_metrics)
        r.add("GET", "/traces\\.json", self.get_traces)
        r.add("GET", "/debug/hotpath\\.json", self.get_hotpath)
        r.add("GET", "/logs\\.json", self.get_logs)
        r.add("GET", "/slo\\.json", self.get_slo)
        r.add("GET", "/qos\\.json", self.get_qos)
        r.add("GET", "/faults\\.json", self.get_faults)
        r.add("GET", "/storage\\.json", self.get_storage)
        r.add("GET", "/healthz", self.healthz)
        r.add("GET", "/readyz", self.readyz)
        r.add("POST", "/webhooks/([^/]+)\\.json", self.webhook_json)
        r.add("POST", "/webhooks/([^/]+)\\.form", self.webhook_form)
        r.add("GET", "/plugins\\.json", self.list_plugins)

    # -- auth ---------------------------------------------------------------
    def invalidate_auth_cache(self) -> None:
        """Drop cached positive key lookups (called on Storage.reset and
        available to key-mutation paths; the TTL still bounds staleness
        for out-of-process mutations). The generation bump fences an
        in-flight ``_auth`` that already read from the OLD store: its
        insert is discarded rather than repopulating the cache with a
        record from a store that no longer exists."""
        with self._auth_cache_lock:
            self._auth_gen += 1
            self._auth_cache.clear()

    def _auth(self, req: Request) -> Tuple[int, Optional[int], tuple]:
        """accessKey+channel → (app_id, channel_id, event_whitelist)."""
        key = req.bearer_key()
        if not key:
            raise HTTPError(401, "missing accessKey")
        now = monotonic_s()
        with self._auth_cache_lock:
            hit = self._auth_cache.get(key)
            gen = self._auth_gen
        ak = hit[1] if hit is not None and hit[0] > now else None
        if ak is None:
            ak = Storage.get_meta_data_access_keys().get(key)
            if ak is not None:
                with self._auth_cache_lock:
                    if len(self._auth_cache) > 4096:
                        self._auth_cache.clear()  # crude bound; refills
                    if self._auth_gen == gen:  # no invalidation raced us
                        self._auth_cache[key] = (
                            now + self.AUTH_CACHE_TTL_S, ak
                        )
        if ak is None:
            raise HTTPError(401, "invalid accessKey")
        channel_id = None
        channel = req.params.get("channel")
        if channel:
            chans = Storage.get_meta_data_channels().get_by_app_id(ak.app_id)
            match = [c for c in chans if c.name == channel]
            if not match:
                raise HTTPError(400, f"invalid channel {channel!r}")
            channel_id = match[0].id
        return ak.app_id, channel_id, ak.events

    def _check_whitelist(self, event_name: str, whitelist: tuple):
        if whitelist and event_name not in whitelist:
            raise HTTPError(
                403, f"accessKey does not allow event {event_name!r}"
            )

    # -- handlers -----------------------------------------------------------
    def alive(self, req: Request):
        return 200, {"status": "alive"}

    # -- health/readiness (ISSUE 2) -----------------------------------------
    def _check_group_commit(self):
        """Liveness via the event store's group committer, when it has
        one: the commit lock must be acquirable (a leader wedged inside
        a hung backend flush holds it forever — see
        :meth:`GroupCommitter.probe`). Backends without group commit
        pass vacuously."""
        try:
            gc = getattr(Storage.get_levents(), "_gc", None)
        except Exception as e:
            return False, f"event store unavailable: {e}"
        if gc is None:
            return True, "no group committer (backend writes directly)"
        return gc.probe(timeout=0.5)

    def _check_storage_ready(self):
        """Readiness: both stores this server writes/authenticates
        against must answer."""
        Storage.get_meta_data_access_keys()
        Storage.get_levents()
        return True, "event + metadata stores reachable"

    def _availability_good_total(self):
        w = self.req_window
        total = w.count
        errors = w.errors
        return total - errors, total

    def healthz(self, req: Request):
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request):
        ok, report = self.health.readiness()
        return (200 if ok else 503), report

    def get_logs(self, req: Request):
        n = int_param(req.params, "n", 100, lo=0, hi=slog.ring().cap)
        try:
            return 200, slog.logs_payload(
                n=n,
                level=req.params.get("level"),
                trace_id=req.params.get("trace_id"),
                logger=req.params.get("logger"),
            )
        except ValueError as e:
            raise HTTPError(400, str(e))

    def get_slo(self, req: Request):
        if self.slo is None:
            return 200, {"slos": [], "configured": False}
        out = self.slo.evaluate()
        out["configured"] = True
        return 200, out

    def get_qos(self, req: Request):
        """Admission-control state (see the query server's twin)."""
        if self.qos is None:
            return 200, {"enabled": False}
        return 200, self.qos.snapshot()

    def get_faults(self, req: Request):
        """Armed failpoints + trigger counts (pio_tpu.faults)."""
        from pio_tpu import faults

        return 200, faults.snapshot()

    def get_storage(self, req: Request):
        """Event-store topology. Backends that can describe themselves
        (the partitioned log's partition table, replication positions
        and snapshot watermarks) do so via a duck-typed ``topology()``;
        everything else reports just its type. This is how the chaos
        drill (and an operator) proves which node is leader and how far
        each follower has acked."""
        try:
            lev = Storage.get_levents()
        except Exception as e:
            raise HTTPError(503, f"event store unavailable: {e}")
        topo = getattr(lev, "topology", None)
        if topo is None:
            return 200, {"backend": type(lev).__name__, "topology": None}
        return 200, topo()

    def _qos_admit(self, req: Request):
        """Admission for the write paths: engine bucket, THEN the
        caller's per-access-key bucket — one chatty key exhausts its own
        budget before it can dent everyone else's. Sheds raise 429/503
        with ``Retry-After`` (ingest has no stale-cache rescue: replaying
        an old write would be a lie, not a degradation)."""
        if self.qos is None:
            return None
        adm = self.qos.admit(
            priority=req.header(PRIORITY_HEADER), key=req.bearer_key()
        )
        if not adm.ok:
            self.qos.count_shed(adm.reason)
            status = (
                429 if adm.reason in ("rate_limit", "key_rate_limit")
                else 503
            )
            raise HTTPError(
                status, f"overloaded: {adm.reason}",
                headers=retry_after_header(adm.retry_after_s),
            )
        return adm

    def _admit_then_auth(self, req: Request):
        """Admission BEFORE auth: the rate limiter exists to shed a
        flood before it reaches the storage-backed access-key lookup, so
        it cannot sit behind that lookup (the 2s positive cache does not
        help a unique-key flood — misses are never cached). The per-key
        bucket keys on the PRESENTED bearer key — a header read, no
        storage — so an invalid key burns its own bucket, not a real
        tenant's. The admission is released if auth then rejects.

        Sheds are still recorded into the request window (they feed the
        SLO engine's error accounting); auth failures are not, matching
        the pre-QoS behavior."""
        t0 = monotonic_s()
        try:
            adm = self._qos_admit(req)
        except HTTPError:
            dur_s = monotonic_s() - t0
            self.req_window.record(dur_s * 1e3, True)
            self._request_cell.observe(dur_s)
            raise
        try:
            app_id, channel_id, whitelist = self._auth(req)
        except BaseException:
            if adm is not None:
                adm.release()
            raise
        return adm, app_id, channel_id, whitelist

    def _guarded_insert(self, fn):
        """Run a storage write through retry + circuit breaker: an open
        breaker fails fast with 503 + Retry-After instead of queueing
        more work onto a dependency that is already drowning, and INSIDE
        a breaker call transient errors (SQLITE_BUSY, a blob server
        mid-restart, injected faults) are retried with jittered backoff —
        the breaker scores the final outcome, so a request saved by a
        retry counts as a success, not ``attempts`` failures."""
        from pio_tpu.storage.retry import retrying

        if self._storage_breaker is None:
            return retrying(fn, site="eventserver.insert")
        call = self._storage_breaker.acquire()
        if not call.allowed:
            self.qos.count_shed("breaker")
            raise HTTPError(
                503, "overloaded: storage circuit breaker open",
                headers=retry_after_header(call.retry_after_s),
            )
        try:
            out = retrying(fn, site="eventserver.insert")
            call.success()
            return out
        except Exception:
            call.failure()
            raise
        finally:
            # releases a half-open probe grant if the call was abandoned
            # (e.g. a BaseException); no-op after success()/failure()
            call.cancel()

    def _validate_one(self, d: Any, app_id: int, channel_id, whitelist,
                      tr=None):
        """JSON → validated Event (whitelist + input blockers applied)."""
        sp = tr.span if tr is not None else (lambda stage: nullcontext())
        with sp("parse"):
            if not isinstance(d, dict):
                raise EventValidationError("event must be a JSON object")
            event = Event.from_api_dict(d)
        with sp("validate"):
            self._check_whitelist(event.event, whitelist)
            for blocker in INPUT_BLOCKERS:
                try:
                    blocker(app_id, channel_id, d)
                except ValueError as e:
                    # input blockers veto with ValueError → client 400
                    raise EventValidationError(str(e))
        return event

    def _post_ingest(self, d: Any, event: Event, app_id: int, channel_id):
        for sniffer in INPUT_SNIFFERS:
            try:
                sniffer(app_id, channel_id, d)
            except Exception:
                log.exception("input sniffer failed")
        self.stats.tick(app_id, event.event, event.entity_type, 201)

    def _ingest_one(self, d: Any, app_id: int, channel_id, whitelist,
                    tr=None) -> str:
        event = self._validate_one(d, app_id, channel_id, whitelist, tr)
        rel_store = tr.elapsed_s if tr is not None else 0.0
        event_id = self._guarded_insert(
            lambda: Storage.get_levents().insert(
                event, app_id, channel_id
            )
        )
        self._post_ingest(d, event, app_id, channel_id)
        if tr is not None:
            # end-aligned through the post-ingest hooks (sniffers,
            # per-app stats) so store tiles flush against write
            tr.add_span(
                "store", tr.elapsed_s - rel_store, rel_start_s=rel_store
            )
        return event_id

    def _begin_waterfall(self, tr, req: Request, t_start: float,
                         t_admitted: float) -> None:
        """Head of every write-path waterfall: the trace opens only
        AFTER admission + auth, so rebase it to the socket read and
        record the accept/admit window it missed."""
        tr.rebase(req.read_s + (t_admitted - t_start))
        tr.add_span("accept", req.read_s, rel_start_s=0.0)
        # end-aligned to NOW, so the trace-open/rebase work just done
        # stays inside the budget instead of leaking between spans
        tr.add_span(
            "admit", tr.elapsed_s - req.read_s, rel_start_s=req.read_s
        )

    def _arm_write_span(self, tr, req: Request) -> None:
        """Tail of the waterfall: record the response write + the TRUE
        end-to-end latency once the bytes hit the socket. The span is
        anchored at HANDLER completion (arm time), not the socket write
        — the return path between them is request time the top-level
        stages must keep tiling."""
        rel_done_s = tr.elapsed_s

        def _written(write_s: float, _tr=tr, _rel=rel_done_s):
            _tr.add_span("write", _tr.elapsed_s - _rel, rel_start_s=_rel)
            _tr.extend_total()
            self._e2e_cell.observe(_tr.elapsed_s, exemplar=_tr.trace_id)

        req.on_written = _written

    def create_event(self, req: Request):
        t_start = monotonic_s()
        # cross-process propagation: a traced caller (e.g. the query
        # server's feedback loop, or a bench client) names the trace this
        # ingest joins — one id spans client, server, and commit leader
        in_tid, in_parent = parse_trace_header(req.header(TRACE_HEADER))
        adm, app_id, channel_id, whitelist = self._admit_then_auth(req)
        t0 = monotonic_s()
        error = True
        trace_id = None
        try:
            with self.tracer.trace(
                "event", trace_id=in_tid, parent=in_parent
            ) as tr:
                trace_id = tr.trace_id
                self._begin_waterfall(tr, req, t_start, t0)
                try:
                    event_id = self._ingest_one(
                        req.body, app_id, channel_id, whitelist, tr
                    )
                except EventValidationError as e:
                    tr.mark_error()
                    self.stats.tick(app_id, "<invalid>", "<invalid>", 400)
                    return 400, {"message": str(e)}
                error = False
                self._arm_write_span(tr, req)
                return 201, json_response(
                    {"eventId": event_id}, {TRACE_HEADER: tr.trace_id}
                )
        finally:
            if adm is not None:
                adm.release()
            dur_s = monotonic_s() - t0
            self.req_window.record(dur_s * 1e3, error)
            self._request_cell.observe(dur_s, exemplar=trace_id)

    def batch_events(self, req: Request):
        t_start = monotonic_s()
        in_tid, in_parent = parse_trace_header(req.header(TRACE_HEADER))
        adm, app_id, channel_id, whitelist = self._admit_then_auth(req)
        try:
            if not isinstance(req.body, list):
                return 400, {"message": "batch body must be a JSON array"}
            if len(req.body) > MAX_BATCH:
                return 400, {
                    "message":
                        f"batch size {len(req.body)} exceeds {MAX_BATCH}"
                }
            t0 = monotonic_s()
            error = True
            trace_id = None
            try:
                with self.tracer.trace(
                    "batch", trace_id=in_tid, parent=in_parent,
                    batchSize=len(req.body),
                ) as tr:
                    trace_id = tr.trace_id
                    self._begin_waterfall(tr, req, t_start, t0)
                    status, results = self._batch_events(
                        req, app_id, channel_id, whitelist, tr
                    )
                    error = False
                    self._arm_write_span(tr, req)
                    return status, json_response(
                        results, {TRACE_HEADER: tr.trace_id}
                    )
            finally:
                dur_s = monotonic_s() - t0
                self.req_window.record(dur_s * 1e3, error)
                self._request_cell.observe(dur_s, exemplar=trace_id)
        finally:
            if adm is not None:
                adm.release()

    def _batch_events(self, req, app_id, channel_id, whitelist, tr):
        # validate every item first (per-item status contract), then land
        # the valid ones in ONE bulk storage write (insert_batch — a
        # single transaction/commit on backends that support it)
        results: list = [None] * len(req.body)
        valid = []
        with tr.span("validate"):
            for k, d in enumerate(req.body):
                try:
                    event = self._validate_one(
                        d, app_id, channel_id, whitelist
                    )
                    valid.append((k, d, event))
                except (EventValidationError, HTTPError) as e:
                    status = e.status if isinstance(e, HTTPError) else 400
                    results[k] = {"status": status, "message": str(e)}
        if valid:
            with tr.span("store"):
                ids = self._guarded_insert(
                    lambda: Storage.get_levents().insert_batch(
                        [e for _, _, e in valid], app_id, channel_id
                    )
                )
            if len(ids) != len(valid):  # a broken backend override must
                # surface as per-item errors, not nulls in the response
                log.error(
                    "insert_batch returned %d ids for %d events",
                    len(ids), len(valid),
                )
                for k, _, _ in valid[len(ids):]:
                    results[k] = {
                        "status": 500,
                        "message": "storage returned no id for this event",
                    }
                valid = valid[: len(ids)]
            for (k, d, event), eid in zip(valid, ids):
                self._post_ingest(d, event, app_id, channel_id)
                results[k] = {"status": 201, "eventId": eid}
        return 200, results

    def get_event(self, req: Request):
        app_id, channel_id, _ = self._auth(req)
        event = Storage.get_levents().get(req.path_args[0], app_id, channel_id)
        if event is None:
            return 404, {"message": "event not found"}
        return 200, event.to_api_dict()

    def delete_event(self, req: Request):
        app_id, channel_id, _ = self._auth(req)
        found = Storage.get_levents().delete(req.path_args[0], app_id, channel_id)
        if not found:
            return 404, {"message": "event not found"}
        return 200, {"message": "deleted"}

    def find_events(self, req: Request):
        app_id, channel_id, _ = self._auth(req)
        p = req.params

        def parse_time(name):
            v = p.get(name)
            if v is None:
                return None
            try:
                return _dt.datetime.fromisoformat(v.replace("Z", "+00:00"))
            except ValueError:
                raise HTTPError(400, f"cannot parse {name}={v!r}")

        limit = _parse_limit(p)
        events = Storage.get_levents().find(
            app_id,
            channel_id=channel_id,
            start_time=parse_time("startTime"),
            until_time=parse_time("untilTime"),
            entity_type=p.get("entityType"),
            entity_id=p.get("entityId"),
            event_names=[p["event"]] if p.get("event") else None,
            target_entity_type=p.get("targetEntityType"),
            target_entity_id=p.get("targetEntityId"),
            limit=limit,
            reversed_order=p.get("reversed", "true").lower() != "false",
        )
        return 200, [e.to_api_dict() for e in events]

    def search_events(self, req: Request):
        """GET /events/search.json?q=<fts query> — BM25-ranked full-text
        search, available when the event store is the searchable backend
        (the Elasticsearch-analog capability, surfaced over REST)."""
        app_id, channel_id, _ = self._auth(req)
        q = req.params.get("q")
        if not q:
            raise HTTPError(400, "missing query param q")
        le = Storage.get_levents()
        if not hasattr(le, "search"):
            raise HTTPError(
                501,
                "the configured event store does not support search; "
                "set the EVENTDATA source TYPE=searchable",
            )
        limit = _parse_limit(req.params)
        from pio_tpu.storage.searchable import SearchError

        try:
            events = le.search(app_id, q, channel_id=channel_id, limit=limit)
        except SearchError as e:
            raise HTTPError(400, str(e))
        return 200, [e.to_api_dict() for e in events]

    def list_plugins(self, req: Request):
        from pio_tpu.server.plugins import installed_plugins

        return 200, installed_plugins()

    def get_stats(self, req: Request):
        """Per-app counters (reference shape) PLUS the query-server
        parity block: request count/errors and latency percentiles for
        the ingest write path; ``?window=SECONDS`` narrows to the
        trailing window (reservoir-backed, like the query server)."""
        window_s = float_param(req.params, "window", 0.0, lo=0.0)
        if window_s > 0:
            return 200, self.req_window.window(window_s)
        out = self.stats.to_dict()
        out.update(self.req_window.to_dict())
        stages = self._stage_summary()
        if stages:
            out["stages"] = stages
        return 200, out

    def _stage_summary(self) -> dict:
        hist = self.tracer.stage_histogram
        out = {}
        if hist is None:
            return out
        for stage in EVENT_STAGES:
            cell = hist.labels(stage)
            n = cell.count
            if n <= 0:
                continue
            q = lambda f: cell.quantile(f)
            out[stage] = {
                "count": int(n),
                "avgMs": round(cell.sum / n * 1e3, 3),
                "p50Ms": _ms(q(0.5)),
                "p95Ms": _ms(q(0.95)),
                "p99Ms": _ms(q(0.99)),
            }
        return out

    def get_metrics(self, req: Request):
        return 200, metrics_response(self.obs.render())

    def _slow_threshold_s(self) -> Optional[float]:
        """Slow-trace capture threshold in seconds (see the query
        server's twin): env override, tightest latency SLO, or the live
        p99 once the distribution has enough mass."""

        ms = knobs.knob_float("PIO_TPU_SLOW_TRACE_MS")
        if ms > 0:
            return ms / 1e3
        slo = self.slo
        if slo is not None:
            thresholds = [
                o.threshold_s for o in slo.objectives
                if o.kind == "latency" and o.threshold_s
            ]
            if thresholds:
                return min(thresholds)
        cell = self._e2e_cell
        if cell.count >= 64:
            return cell.quantile(0.99, pool=False)
        return None

    def get_hotpath(self, req: Request):
        """Per-stage latency budget of the ingest write paths (count/
        avg/p50/p95 + attributed fraction of the end-to-end average)."""
        return 200, hotpath_payload(
            self.tracer, self._e2e_cell,
            stage_order=EVENT_STAGES + EVENT_SUBSTAGES, pool=False,
            slow_threshold_s=self._slow_threshold_s(),
        )

    def get_traces(self, req: Request):
        """Recent ingest traces, slowest first, MERGED with the group-
        commit leader's flush traces (each links the member requests it
        carried — the cross-process join of the event path). ``?slow=1``
        serves the tail-capture ring; ``?id=`` looks up one trace across
        the request, slow, and commit rings; ``?commits=0`` restricts to
        request traces."""
        from pio_tpu.storage.groupcommit import COMMIT_TRACER

        n = int_param(req.params, "n", 20, lo=0, hi=self.tracer._ring_cap)
        tid = req.params.get("id")
        if tid:
            found = self.tracer.find(tid) or COMMIT_TRACER.find(tid)
            if found is None:
                raise HTTPError(404, f"trace {tid} not in any ring")
            return 200, {"traces": [found]}
        if req.params.get("slow") in ("1", "true"):
            return 200, {"traces": self.tracer.slow(n)}
        order = req.params.get("order", "slowest")
        slowest = order != "recent"
        traces = self.tracer.recent(n, slowest=slowest)
        if req.params.get("commits", "1") != "0":
            traces += COMMIT_TRACER.recent(n, slowest=slowest)
            key = (
                (lambda t: t.get("totalMs") or 0.0) if slowest
                else (lambda t: t.get("wallTime") or 0.0)
            )
            traces = sorted(traces, key=key, reverse=True)[:n]
        return 200, {"traces": traces}

    def webhook_json(self, req: Request):
        t_start = monotonic_s()
        in_tid, in_parent = parse_trace_header(req.header(TRACE_HEADER))
        adm, app_id, channel_id, whitelist = self._admit_then_auth(req)
        try:
            connector = JSON_CONNECTORS.get(req.path_args[0])
            if connector is None:
                return 404, {
                    "message": f"no JSON connector {req.path_args[0]!r}"
                }
            if req.body is not None and not isinstance(req.body, dict):
                return 400, {
                    "message": "webhook payload must be a JSON object"
                }
            t0 = monotonic_s()
            error = True
            trace_id = None
            try:
                with self.tracer.trace(
                    "webhook", trace_id=in_tid, parent=in_parent
                ) as tr:
                    trace_id = tr.trace_id
                    self._begin_waterfall(tr, req, t_start, t0)
                    try:
                        d = connector.to_event_dict(req.body or {})
                        event_id = self._ingest_one(
                            d, app_id, channel_id, whitelist, tr
                        )
                    except (ConnectorError, EventValidationError) as e:
                        tr.mark_error()
                        return 400, {"message": str(e)}
                    error = False
                    self._arm_write_span(tr, req)
                    return 201, json_response(
                        {"eventId": event_id}, {TRACE_HEADER: tr.trace_id}
                    )
            finally:
                dur_s = monotonic_s() - t0
                self.req_window.record(dur_s * 1e3, error)
                self._request_cell.observe(dur_s, exemplar=trace_id)
        finally:
            if adm is not None:
                adm.release()

    def webhook_form(self, req: Request):
        t_start = monotonic_s()
        in_tid, in_parent = parse_trace_header(req.header(TRACE_HEADER))
        adm, app_id, channel_id, whitelist = self._admit_then_auth(req)
        try:
            connector = FORM_CONNECTORS.get(req.path_args[0])
            if connector is None:
                return 404, {
                    "message": f"no form connector {req.path_args[0]!r}"
                }
            form = parse_form(
                req.raw_body.decode("utf-8", errors="replace")
                if req.raw_body
                else ""
            )
            t0 = monotonic_s()
            error = True
            trace_id = None
            try:
                with self.tracer.trace(
                    "webhook", trace_id=in_tid, parent=in_parent
                ) as tr:
                    trace_id = tr.trace_id
                    self._begin_waterfall(tr, req, t_start, t0)
                    try:
                        d = connector.to_event_dict(form)
                        event_id = self._ingest_one(
                            d, app_id, channel_id, whitelist, tr
                        )
                    except (ConnectorError, EventValidationError) as e:
                        tr.mark_error()
                        return 400, {"message": str(e)}
                    error = False
                    self._arm_write_span(tr, req)
                    return 201, json_response(
                        {"eventId": event_id}, {TRACE_HEADER: tr.trace_id}
                    )
            finally:
                dur_s = monotonic_s() - t0
                self.req_window.record(dur_s * 1e3, error)
                self._request_cell.observe(dur_s, exemplar=trace_id)
        finally:
            if adm is not None:
                adm.release()


def create_event_server(
    host: str = "0.0.0.0", port: int = 7070,
    slos: Optional[List[str]] = None,
    qos: Optional[Any] = None,
) -> JsonHTTPServer:
    """Build (unstarted) server — reference ``EventServer.createEventServer``."""
    from pio_tpu.server.plugins import load_plugins_from_env

    load_plugins_from_env()
    service = EventServerService(slos=slos, qos=qos)
    server = JsonHTTPServer(
        service.router, host, port, name="pio-tpu-eventserver"
    )
    server.service = service  # reachable for embedding/tests
    return server
