"""Query Server — per-engine HTTP serving daemon (:8000 by default).

Rebuild of the reference's ``core/.../workflow/CreateServer.scala``
(MasterActor/ServerActor — UNVERIFIED path; see SURVEY.md). Routes:

    GET  /               status (engine, instance, uptime, request counts)
    POST /queries.json   typed query → serving.serve over all algorithms
    GET  /stats.json     request count + latency stats
    POST /reload         hot-swap to the latest COMPLETED engine instance
    POST /undeploy       stop accepting queries (reference `pio undeploy`)

Queries bind to the algorithm's declared ``query_class`` dataclass (the
JsonExtractor queryClassTag analog); responses use ``to_dict()`` when the
prediction provides it. When ``feedback`` is enabled, every response is
logged back to the event store as a ``predict`` event on entity type
``pio_pr`` carrying the prId — the reference's feedback loop.
"""

from __future__ import annotations

import collections
import dataclasses
import datetime as _dt
import json
import logging
import threading
import uuid
from typing import Any, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.analysis.runtime import make_condition, make_lock
from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.controller.params import ParamsError, params_from_dict
from pio_tpu.data.event import Event
from pio_tpu.faults import failpoint
from pio_tpu.obs import (
    Heartbeat, HealthMonitor, MetricsRegistry, RequestWindow, TRACE_HEADER,
    Tracer, add_active_span, hotpath_payload, monotonic_s,
    parse_trace_header,
)
from pio_tpu.obs import devicewatch, slog
from pio_tpu.obs.profile import DeviceProfileHook
from pio_tpu.obs.slo import engine_for_specs
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.qos import (
    DEADLINE_HEADER, DEGRADED_HEADER, DEGRADED_VALUE, PRIORITY_HEADER,
    Deadline, DeadlineExceeded, QoSGate, cache_key, resolve_policy,
    retry_after_header,
)
from pio_tpu.server.batchlane import (
    BatchLaneSegment, LaneClient, LaneDrainer, LaneFallback, PackedQuery,
    pack_query_i8, packed_frame_ok, unpack_query_i8,
)
from pio_tpu.server.bucketcache import (
    BucketExecutionCache, dispatch_bucketed,
)
from pio_tpu.server.http import (
    HTTPError, JsonHTTPServer, RawResponse, Request, Router, float_param,
    int_param, json_response, keys_equal, metrics_response,
    ssl_context_from_env,
)
from pio_tpu.storage import Storage
from pio_tpu.workflow.core_workflow import load_models_for_instance
from pio_tpu.workflow.deploy_common import (
    resolve_instance_id,
    resolve_query_class,
    to_jsonable as _to_jsonable,
)
from pio_tpu.workflow.engine_json import EngineVariant, build_engine

log = logging.getLogger("pio_tpu.queryserver")

#: query-path plugin hooks (reference EngineServerPlugin)
QUERY_BLOCKERS: List = []
QUERY_SNIFFERS: List = []

#: sentinel: the micro-batch dispatch failed; the waiting request thread
#: runs the per-query fallback itself (see _MicroBatcher.submit)
_BATCH_FAILED = object()

#: query-path trace stages, in request order: socket read + body parse
#: (measured by the HTTP layer), QoS admission, JSON binding +
#: serving.supplement, micro-batch queue wait, device/model execute,
#: response serialization (to_jsonable + hooks + feedback), response
#: write. Top-level stages TILE the request — their durations sum to the
#: end-to-end latency — which is what /debug/hotpath.json budgets against.
QUERY_STAGES = (
    "accept", "admit", "parse", "queue", "execute", "serialize", "write",
)

#: dotted substages attribute time WITHIN a top-level stage (excluded
#: from budget sums — the microseconds are already counted above).
#: Pre-declared so their histogram cells exist at pool-bind time.
QUERY_SUBSTAGES = ("admit.queue", "execute.device")


def _stripe_generation_lines(seg) -> list:
    """Exposition lines for ``pio_tpu_pool_stripe_generation`` — read
    fresh from the shared segment at every scrape (the supervisor, a
    different process, owns the generation words)."""
    lines = [
        "# HELP pio_tpu_pool_stripe_generation Pool metrics stripe "
        "ownership generation per worker slot (bumped at every respawn; "
        "negative = retired, totals frozen)",
        "# TYPE pio_tpu_pool_stripe_generation gauge",
    ]
    for w, g in enumerate(seg.generations()):
        lines.append(
            f'pio_tpu_pool_stripe_generation{{worker="{w}"}} {g}'
        )
    return lines


def _q_ms(cell, q: float):
    """Histogram-cell quantile in milliseconds (None when empty)."""
    v = cell.quantile(q)
    return round(v * 1e3, 3) if v is not None else None


class _MicroBatcher:
    """Coalesces concurrent ``/queries.json`` requests into one
    ``algo.batch_predict`` dispatch — WHEN that wins.

    The reference serves strictly per-request (one ``predictBase`` per
    HTTP call on the driver JVM). On an accelerator the per-dispatch
    round trip dominates single-query cost, so under concurrent load it
    pays to aggregate: request threads enqueue their (already parsed +
    supplemented) query and block; a worker drains the queue after a
    short collection window and pushes the whole batch through each
    algorithm's ``batch_predict`` — for factor-serving templates that is
    ONE ``[B, K] @ [K, N]`` device matmul + top-k instead of B separate
    dispatches — then serves each query individually.

    **Adaptive bypass.** Whether coalescing wins depends on the deploy:
    on a device-resident scorer with real per-dispatch RTT it does; on a
    host-mirror scorer the extra condition-variable handoffs can cost
    more than the batched matmul saves (measured losing in the round-3
    driver bench). Predicting that from first principles is guesswork,
    so the batcher measures it live: the first ``PROBE_QUERIES``
    requests run coalesced, the next ``PROBE_QUERIES`` run per-request
    in the caller's thread, and whichever regime had the lower median
    request latency under the SAME live load becomes permanent
    (Little's law: under fixed concurrency, lower mean latency ⇔ higher
    throughput). ``PIO_TPU_SERVE_MICROBATCH_ADAPTIVE=0`` pins it on.

    Enabled via ``PIO_TPU_SERVE_MICROBATCH_US`` (collection window in
    microseconds; unset/0 = off, classic per-request path). If a batch
    dispatch fails, every member falls back to the per-query path so one
    poisoned query cannot fail its batch-mates.
    """

    MAX_BATCH = 512
    #: dispatch this far BEFORE the tightest queued deadline: waking at
    #: the exact expiry instant would shed the very member the deadline
    #: bound exists to protect (cond.wait also overshoots under load).
    #: A member whose remaining budget is already under the slack
    #: dispatches immediately instead of waiting out the window.
    DEADLINE_SLACK_S = 0.05
    #: probe sample size per regime before the permanent mode decision.
    #: Only the chronologically LAST half of each window is compared —
    #: the first batches of a fresh deploy pay one-off XLA bucket
    #: compiles (seconds-scale) that would otherwise poison the batched
    #: median and lock in "off" exactly where coalescing wins.
    PROBE_QUERIES = 96

    def __init__(self, service: "QueryServerService", window_s: float,
                 adaptive: bool = True):
        self._service = service
        self._window_s = window_s
        self._cv = make_condition("query.microbatch")
        self._queue: List[list] = []
        self._stopped = False
        self.batches = 0
        self.batched_queries = 0
        self.max_batch = 0
        #: probe_batch → probe_solo → on | off
        self._mode = "probe_batch" if adaptive else "on"
        #: set when the probe decides "off" — query() then skips the
        #: batcher entirely (inline per-request path, no residual cost)
        self.bypassed = False
        #: an "off" verdict is re-examined this often: the early probe
        #: can catch compile transients / cold caches that a warmed
        #: server has long outgrown — "off" is a lease, not a latch
        #: (0 disables re-probing and restores the one-shot behavior)
        self._reprobe_s = knobs.knob_float("PIO_TPU_MB_REPROBE_S")
        self._decided_at = 0.0
        self.reprobes = 0
        self._probe_lock = make_lock("query.microbatch.probe")
        self._probe: dict = {"batch": [], "solo": []}
        #: per-bucket batched per-member latency samples (bounded ring,
        #: fresh-bucket dispatches excluded) — the post-warmup honesty
        #: map behind ``modeByBucket``: the single ``mode`` string is
        #: one global verdict, but whether coalescing wins is a
        #: PER-BUCKET question (a 64-wide dispatch amortizes RTT that a
        #: 1-wide dispatch only adds handoffs to)
        self._bucket_samples: dict = {}
        self._thread = threading.Thread(
            target=self._run, name="pio-tpu-microbatch", daemon=True
        )
        self._thread.start()

    def active(self) -> bool:
        """Should queries flow through the batcher? Cheap hot-path check
        that doubles as the re-probe trigger: once an "off" verdict has
        aged past the re-probe interval, the probe windows reset and the
        next requests measure again — a verdict poisoned by deploy-time
        transients (bucket compiles, cold caches) heals instead of
        sticking for the server's lifetime."""
        if not self.bypassed:
            return True
        if self._reprobe_s <= 0 \
                or monotonic_s() - self._decided_at < self._reprobe_s:
            return False
        with self._probe_lock:
            if not self.bypassed:  # another thread re-armed first
                return True
            self._probe = {"batch": [], "solo": []}
            self._mode = "probe_batch"
            self.bypassed = False
            self.reprobes += 1
        log.info(
            "micro-batch re-probe: re-measuring after %.0fs in bypass",
            self._reprobe_s,
        )
        return True

    def submit(self, query, span_sink=None, deadline=None):  # pio: hotpath
        """Serve one query through the current regime; blocks until done.
        If the batch dispatch failed, the fallback per-query predict runs
        HERE — in the request's own thread — so one poisoned query
        degrades its batch-mates to ordinary concurrent serving, not to a
        serial queue behind the single worker.

        ``span_sink`` (a trace handle with ``add_span``) receives the
        queue-wait and execute stage timings measured where they actually
        happen — the worker thread computes per-member queue wait at
        drain time and the shared batch dispatch duration.

        ``deadline`` (a :class:`pio_tpu.qos.Deadline`, optional) rides
        along in the pend entry: the worker sheds members whose budget
        elapsed in queue BEFORE dispatching the batch (raised here as
        ``DeadlineExceeded``) and never stretches the collection window
        past the tightest queued deadline."""
        mode = self._mode
        if mode == "off" or mode == "probe_solo":
            t0 = monotonic_s()
            out = self._service._predict_one(query)
            dt = monotonic_s() - t0
            if span_sink is not None:
                span_sink.add_span("queue", 0.0)
                span_sink.add_span("execute", dt)
            if mode == "probe_solo":
                self._note_probe("solo", dt)
            return out
        t0 = monotonic_s()
        # q, result, exc, done, enqueue_t, stage timings (worker-filled),
        # deadline, member trace id (the batch trace links its members)
        pend = [query, None, None, threading.Event(), t0, {}, deadline,
                span_sink.trace_id if span_sink is not None else None]
        with self._cv:
            if self._stopped:
                raise HTTPError(503, "undeployed")
            self._queue.append(pend)
            self._cv.notify()
        # submit IS the synchronous rendezvous: the request thread
        # parks until its batch completes
        # pio: disable=hotpath-blocking
        pend[3].wait()
        if mode == "probe_batch" and not pend[5].get("fresh_bucket"):
            # a dispatch that compiled a fresh shape bucket is a one-off
            # deploy transient, not the steady state the probe compares —
            # discard the whole batch's samples (satellite of ISSUE 7:
            # the old probe latched "off" on exactly these)
            self._note_probe("batch", monotonic_s() - t0)
        if span_sink is not None and "queue_s" in pend[5]:
            span_sink.add_span("queue", pend[5]["queue_s"])
        if span_sink is not None and "batch_id" in pend[5]:
            # back-link: the member's waterfall names the batch trace
            # whose execute span it shared
            span_sink.note(microbatch=pend[5]["batch_id"])
        if pend[2] is _BATCH_FAILED:
            t1 = monotonic_s()
            out = self._service._predict_one(pend[0])
            if span_sink is not None:
                span_sink.add_span("execute", monotonic_s() - t1)
            return out
        if span_sink is not None and "execute_s" in pend[5]:
            span_sink.add_span("execute", pend[5]["execute_s"])
        if pend[2] is not None:
            raise pend[2]
        return pend[1]

    def _note_probe(self, kind: str, dt: float) -> None:
        with self._probe_lock:
            samples = self._probe[kind]
            samples.append(dt)
            if len(samples) < self.PROBE_QUERIES:
                return
            if kind == "batch" and self._mode == "probe_batch":
                self._mode = "probe_solo"
            elif kind == "solo" and self._mode == "probe_solo":
                # steady-state comparison: drop each window's first half
                # (bucket-compile and cache warmup transients land there)
                med = lambda xs: sorted(xs[len(xs) // 2:])[len(xs) // 4]
                batch_med = med(self._probe["batch"])
                solo_med = med(self._probe["solo"])
                self._mode = "on" if batch_med <= solo_med else "off"
                log.info(
                    "micro-batch probe: batched p50 %.3f ms vs per-query "
                    "p50 %.3f ms under live load -> %s",
                    batch_med * 1e3, solo_med * 1e3, self._mode,
                )
                if self._mode == "off":
                    # true bypass: the query path re-checks this flag and
                    # goes back to inline per-request serving, byte-for-
                    # byte the no-batcher code path (zero residual cost
                    # beyond the aged-verdict check in active())
                    self.bypassed = True
                    self._decided_at = monotonic_s()

    @property
    def mode(self) -> str:
        """Current regime (lock-free read — for cheap polling)."""
        return self._mode

    def stop(self):
        with self._cv:
            self._stopped = True
            self._cv.notify()

    def to_dict(self) -> dict:
        with self._probe_lock:
            med = lambda xs: (
                round(sorted(xs)[len(xs) // 2] * 1e3, 3) if xs else None
            )
            probe = {
                "batchedP50Ms": med(self._probe["batch"]),
                "perQueryP50Ms": med(self._probe["solo"]),
            }
            solo = sorted(self._probe["solo"])
            solo_med = solo[len(solo) // 2] if solo else None
        # post-warmup per-bucket verdict: each bucket's batched
        # per-member p50 against the probe's per-query p50 — the honest
        # answer to "which batch sizes is coalescing actually winning
        # at", where the single `mode` string collapses them all
        mode_by_bucket = {}
        for b in sorted(self._bucket_samples):
            xs = sorted(self._bucket_samples[b])
            if not xs:
                continue
            p50 = xs[len(xs) // 2]
            mode_by_bucket[str(b)] = {
                "mode": (
                    "on" if solo_med is None or p50 <= solo_med else "off"
                ),
                "p50Ms": round(p50 * 1e3, 3),
                "samples": len(xs),
            }
        return {
            "mode": self._mode,
            "modeByBucket": mode_by_bucket,
            "probe": probe,
            "batches": self.batches,
            "batchedQueries": self.batched_queries,
            "maxBatch": self.max_batch,
            "windowUs": round(self._window_s * 1e6),
            "reprobeSeconds": self._reprobe_s,
            "reprobes": self.reprobes,
            "bypassed": self.bypassed,
        }

    def _run(self):  # pio: hotpath
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    # idle park: nothing to batch until an enqueue
                    # notifies
                    # pio: disable=hotpath-blocking
                    self._cv.wait()
                if self._stopped and not self._queue:
                    return
            # collection window: let concurrent request threads pile on —
            # but don't idle when a full batch is already waiting, and
            # never wait past the tightest queued deadline (the batch
            # honors its most impatient member). Waiting happens on the
            # condition variable, NOT a blind sleep: every enqueue
            # notifies, so a member arriving mid-window with a TIGHTER
            # deadline re-shortens the wait instead of expiring in queue
            # behind a window computed before it existed.
            if self._window_s > 0:
                window_end = monotonic_s() + self._window_s
                with self._cv:
                    while not self._stopped \
                            and len(self._queue) < self.MAX_BATCH:
                        wait_s = window_end - monotonic_s()
                        tightest = min(
                            (p[6].remaining_s() for p in self._queue
                             if p[6] is not None),
                            default=None,
                        )
                        if tightest is not None:
                            wait_s = min(
                                wait_s, tightest - self.DEADLINE_SLACK_S
                            )
                        if wait_s <= 0:
                            break
                        # deadline-bounded collection window (see
                        # comment above) — not a blind stall
                        # pio: disable=hotpath-blocking
                        self._cv.wait(wait_s)
            with self._cv:
                batch = self._queue[: self.MAX_BATCH]
                del self._queue[: len(batch)]
            if not batch:
                continue
            # stage attribution: everything before the drain is queue
            # wait (per member — each enqueued at its own time), the
            # shared dispatch below is each member's execute time
            t_drain = monotonic_s()
            for p in batch:
                p[5]["queue_s"] = max(t_drain - p[4], 0.0)
            # deadline shedding: a member whose budget elapsed in queue
            # is failed HERE, before the model runs — its client already
            # gave up, and executing it would only slow its batch-mates
            live = []
            for p in batch:
                if p[6] is not None and p[6].expired():
                    p[2] = DeadlineExceeded("deadline elapsed in queue")
                    p[3].set()
                else:
                    live.append(p)
            batch = live
            if not batch:
                continue
            self.batches += 1
            self.batched_queries += len(batch)
            self.max_batch = max(self.max_batch, len(batch))
            try:
                # the batch dispatch gets ONE trace linking every member
                # request trace — "which requests shared this dispatch"
                # becomes answerable from /traces.json. Device time lands
                # on it as execute.device via the active-trace contextvar.
                queries = [p[0] for p in batch]
                # freshness from the warmed-set snapshot, not the
                # dispatch return: _predict_batch is the seam tests and
                # profilers wrap, so the batcher must go through it
                cache = self._service._buckets
                pre_warmed = cache.warmed
                fresh = any(
                    cache.bucket_for(n) not in pre_warmed
                    for n in cache.chunks(len(queries))
                )
                with self._service.tracer.trace(
                    "microbatch",
                    links=[p[7] for p in batch if p[7]],
                    batch=len(batch),
                ) as btr:
                    results = self._service._predict_batch(queries)
                exec_s = monotonic_s() - t_drain
                bucket = cache.bucket_for(len(batch))
                samples = self._bucket_samples.get(bucket)
                if samples is None:
                    samples = self._bucket_samples[bucket] = (
                        collections.deque(maxlen=64)
                    )
                for p, r in zip(batch, results):
                    p[1] = r
                    p[5]["execute_s"] = exec_s
                    p[5]["batch_id"] = btr.trace_id
                    if fresh:
                        # this dispatch paid a bucket compile — flag every
                        # member so the probe discards the transient
                        p[5]["fresh_bucket"] = True
                    else:
                        # per-member request latency (queue + execute)
                        # under this bucket, steady-state samples only
                        samples.append(p[5]["queue_s"] + exec_s)
            except Exception:
                log.exception(
                    "micro-batch dispatch failed; per-query fallback "
                    "(runs in each request's own thread)"
                )
                for p in batch:
                    p[2] = _BATCH_FAILED
            for p in batch:
                p[3].set()


class QueryServerService:
    """The ServerActor analog; MasterActor duties (reload/undeploy) included."""

    def __init__(
        self,
        variant: EngineVariant,
        instance_id: Optional[str] = None,
        ctx: Optional[ComputeContext] = None,
        feedback: bool = False,
        feedback_app_id: Optional[int] = None,
        admin_key: Optional[str] = None,
        slos: Optional[List[str]] = None,
        qos: Optional[Any] = None,
    ):
        self.variant = variant
        self.ctx = ctx or ComputeContext.create()
        self.feedback = feedback
        self.feedback_app_id = feedback_app_id
        #: guards /reload and /undeploy; without a key only loopback clients
        #: may call them (the default bind is 0.0.0.0)
        self.admin_key = admin_key
        self.start_time = _dt.datetime.now(_dt.timezone.utc)
        #: per-instance registry (not the process-global one) so embedded
        #: test servers never cross-pollinate each other's scrapes
        self.obs = MetricsRegistry()
        eng = variant.engine_id
        self._queries_total = self.obs.counter(
            "pio_tpu_queries_total", "Queries served", ("engine_id",)
        )
        self._query_errors_total = self.obs.counter(
            "pio_tpu_query_errors_total", "Queries that errored", ("engine_id",)
        )
        #: full-request latency histogram — the SLO engine's latency
        #: source (stage histograms cover WHERE time went; this one
        #: covers the request the client saw)
        self._request_hist = self.obs.histogram(
            "pio_tpu_request_seconds",
            "Full-request wall seconds of /queries.json",
            ("engine_id",),
        )
        #: end-to-end latency histogram (accept→write, stamped from the
        #: post-write hook): what the CLIENT saw, and the denominator of
        #: the /debug/hotpath.json attribution budget
        self._e2e_hist = self.obs.histogram(
            "pio_tpu_e2e_seconds",
            "End-to-end wall seconds of /queries.json (socket read "
            "through response write)",
            ("engine_id",),
        )
        # pre-create the cells so pool-mode slot layout sees them at init
        self._queries_total.labels(eng)
        self._query_errors_total.labels(eng)
        self._request_cell = self._request_hist.labels(eng)
        self._e2e_cell = self._e2e_hist.labels(eng)
        #: set by mark_evloop_front() when the evloop HTTP front serves
        #: this service: handlers run inline in the event loop, so the
        #: micro-batcher's blocking hand-off must be bypassed
        self._evloop_front = False
        self._parse_fastpath_total = self.obs.counter(
            "pio_tpu_http_parse_fastpath_total",
            "Packed binary query requests by outcome: hit = zero-copy "
            "socket→lane frame, local = served by the local packed "
            "fallback, invalid = malformed frame (400), unavailable = "
            "no single int8 resident scorer to decode it (400)",
            ("outcome",),
        )
        #: bound outcome cells — the packed hot path bumps one per
        #: request; labels() resolution there would cost more than the
        #: increment (see _Cell.inc)
        self._fastpath_cells = {
            outcome: self._parse_fastpath_total.labels(outcome)
            for outcome in ("hit", "local", "invalid", "unavailable")
        }
        self.tracer = Tracer(
            "query", registry=self.obs,
            stages=QUERY_STAGES + QUERY_SUBSTAGES,
            extra_labels={"engine_id": eng},
        )
        # tail-based slow-trace capture: threshold from (in order) the
        # PIO_TPU_SLOW_TRACE_MS override, the tightest latency SLO, or
        # the live p99 estimate once there is enough signal
        self.tracer.slow_threshold_fn = self._slow_threshold_s
        self.stats = RequestWindow()
        self.obs.add_collector(self._compat_metric_lines)
        # structured-log ring (process-wide install is record-only; the
        # CLI switches console rendering) + log-volume counter re-export
        slog.install()
        self.obs.add_collector(slog.exposition_lines)
        from pio_tpu import faults as _faults

        self.obs.add_collector(_faults.exposition_lines)
        # -- health probes (ISSUE 2) --
        self.heartbeat = Heartbeat(max_age_s=knobs.knob_float(
            "PIO_TPU_HEARTBEAT_MAX_AGE_S"
        ))
        self.health = HealthMonitor()
        self.health.add_liveness("http_loop", self._http_loop_alive)
        self.health.add_critical_thread(
            "microbatch_worker",
            lambda: getattr(self._batcher, "_thread", None),
        )
        self.health.add_readiness("engine", self._check_engine_ready)
        self.health.add_readiness("storage", self._check_storage_ready)
        # -- SLO engine (ISSUE 2): specs from the caller or PIO_TPU_SLO --
        if slos is None:
            env_slos = knobs.knob_str("PIO_TPU_SLO")
            slos = [s for s in env_slos.split(",") if s.strip()]
        self.slo = None
        if slos:
            self.slo = engine_for_specs(
                slos, self.obs,
                availability_source=self._availability_good_total,
                latency_cell_getter=lambda: self._request_cell,
            )
        # -- QoS (ISSUE 3): admission control, deadlines, degradation.
        # The gate's counters MUST be created here (before any
        # enable_pool bind) so its shed/admitted cells land in the shared
        # segment and the rps= budget is enforced POOL-WIDE.
        policy = resolve_policy(qos, variant.variant)
        self.qos = (
            QoSGate(policy, self.obs, scope="queryserver")
            if policy is not None else None
        )
        self._scorer_breaker = (
            self.qos.breaker("scorer") if self.qos is not None else None
        )
        # -- shape-bucket execution cache (ISSUE 7): every batched
        # dispatch is padded to a fixed bucket ladder so steady-state
        # serving never retraces; the warmup sweep in _load compiles the
        # ladder at deploy. Metrics MUST be created (and their label
        # cells pre-created) here, before any enable_pool bind, so the
        # retrace/dispatch counters land in the shared segment.
        self._buckets = BucketExecutionCache()
        self._bucket_dispatch_total = self.obs.counter(
            "pio_tpu_bucket_dispatch_total",
            "Batched dispatches by shape bucket (padded batch size)",
            ("engine_id", "bucket"),
        )
        self._bucket_retrace_total = self.obs.counter(
            "pio_tpu_bucket_retrace_total",
            "Batched dispatches that hit a cold shape bucket (paid an "
            "XLA trace+compile the warmup sweep should have absorbed); "
            "flat in steady state",
            ("engine_id",),
        )
        self._bucket_evictions_total = self.obs.counter(
            "pio_tpu_bucket_evictions_total",
            "Model hot-swaps that evicted the previous generation's "
            "warmed bucket entries",
            ("engine_id",),
        )
        self._bucket_occupancy = self.obs.histogram(
            "pio_tpu_bucket_occupancy_ratio",
            "Real batch size over bucket size per dispatch (1.0 = no "
            "padding waste)",
            ("engine_id",),
            buckets=(0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self._bucket_entries = self.obs.gauge(
            "pio_tpu_bucket_entries",
            "Warmed shape-bucket entries for the deployed generation",
            ("engine_id",),
        )
        for b in self._buckets.buckets:
            self._bucket_dispatch_total.labels(eng, str(b))
        self._bucket_retrace_total.labels(eng)
        self._bucket_evictions_total.labels(eng)
        self._bucket_occ_cell = self._bucket_occupancy.labels(eng)
        self._bucket_entries.labels(eng)
        # -- cross-worker batch lane (ISSUE 7): wired by
        # enable_batch_lane() in pool mode; counters declared up front
        # for the same pool-bind reason as above
        self._lane_client = None
        self._lane_drainer = None
        self._lane_seg = None
        self._lane_enqueued_total = self.obs.counter(
            "pio_tpu_batchlane_enqueued_total",
            "Queries this worker served through the shared-memory batch "
            "lane (answered by the device worker's bucketed dispatch)",
            ("engine_id",),
        )
        self._lane_drained_total = self.obs.counter(
            "pio_tpu_batchlane_drained_total",
            "Lane requests the device worker drained across all stripes",
            ("engine_id",),
        )
        self._lane_batches_total = self.obs.counter(
            "pio_tpu_batchlane_batches_total",
            "Cross-worker lane drain cycles served as one bucketed "
            "dispatch",
            ("engine_id",),
        )
        self._lane_full_total = self.obs.counter(
            "pio_tpu_batchlane_full_total",
            "Lane submissions that fell back to local predict because "
            "this worker's stripe had no free slot",
            ("engine_id",),
        )
        self._lane_fallback_total = self.obs.counter(
            "pio_tpu_batchlane_fallback_total",
            "Lane submissions served by the local fallback path, by "
            "reason (full, timeout, oversize, remote_error, ...)",
            ("engine_id", "reason"),
        )
        self._lane_depth = self.obs.gauge(
            "pio_tpu_batchlane_depth",
            "Unanswered lane requests across all stripes at last drain",
            ("engine_id",),
        )
        self._lane_enqueued_total.labels(eng)
        self._lane_drained_total.labels(eng)
        self._lane_batches_total.labels(eng)
        self._lane_full_total.labels(eng)
        for reason in ("full", "timeout", "oversize", "remote_error",
                       "unserializable", "undecodable_response"):
            self._lane_fallback_total.labels(eng, reason)
        # -- device-resident serving (ISSUE 8): params placed on device
        # once per generation, donated per-bucket dispatch buffers, int8
        # query wire. Counters pre-created before any pool bind, same as
        # the bucket/lane families above.
        self._resident: List = []
        self._h2d_bytes_total = self.obs.counter(
            "pio_tpu_serving_h2d_bytes_total",
            "Host→device feature bytes shipped by resident-scorer "
            "dispatches (the int8 wire pays one byte per feature per "
            "query; float32 pays four)",
            ("engine_id",),
        )
        self._donation_total = self.obs.counter(
            "pio_tpu_donation_total",
            "Donated-buffer dispatch outcomes: hit = recycled the "
            "standing per-bucket device buffer, miss = cold shape had "
            "to allocate (once per bucket per generation)",
            ("engine_id", "outcome"),
        )
        self._resident_params_bytes = self.obs.gauge(
            "pio_tpu_resident_params_bytes",
            "Device-resident serving parameter bytes for the deployed "
            "generation (0 = host-mirror serving)",
            ("engine_id",),
        )
        self._resident_models = self.obs.gauge(
            "pio_tpu_resident_models",
            "Models in the deployed generation serving from "
            "device-resident params",
            ("engine_id",),
        )
        self._h2d_bytes_total.labels(eng)
        for outcome in ("hit", "miss"):
            self._donation_total.labels(eng, outcome)
        self._resident_params_bytes.labels(eng)
        self._resident_models.labels(eng)
        # -- mesh-sharded serving (ISSUE 10): factor tables partitioned
        # over the serving mesh via the partition-rule registry
        # (PIO_TPU_MESH_SERVE gate). Counters pre-created before any
        # pool bind, same as the families above.
        self._sharding_info: Optional[dict] = None
        self._shard_bytes_placed_total = self.obs.counter(
            "pio_tpu_shard_bytes_placed_total",
            "Model parameter bytes placed sharded across the serving "
            "mesh (summed over devices, once per deploy generation)",
            ("engine_id",),
        )
        self._shard_gather_fallback_total = self.obs.counter(
            "pio_tpu_shard_gather_fallback_total",
            "Mesh placements that fell back to single-device/replicated "
            "serving (budget exceeded, indivisible shapes, or placement "
            "error)",
            ("engine_id",),
        )
        self._shard_bytes_placed_total.labels(eng)
        self._shard_gather_fallback_total.labels(eng)
        # -- device telemetry plane (ISSUE 17): per-instance watch on
        # this registry (DeviceWatch pre-creates its compile site cells,
        # so the families exist before any pool bind like the counters
        # above). Module activation routes the residency/stream/shard
        # ledger hooks here; the sampler thread keeps memory_stats
        # reads OFF the dispatch path (PIO_TPU_DEVICEWATCH=0 keeps the
        # thread off — /device.json then samples on demand).
        self.devwatch = devicewatch.DeviceWatch(registry=self.obs)
        devicewatch.activate(self.devwatch)
        if knobs.knob_str(devicewatch.SAMPLER_ENV) != "0":
            self.devwatch.start()
        self.profile_hook = DeviceProfileHook.from_env()
        self._swap_lock = make_lock("query.model_swap")
        self._deployed = True
        #: pool mode (see server/worker_pool.py): shared reload generation
        #: + shutdown event wired in by enable_pool()
        self._pool_idx = None
        self._pool_size = None
        self._pool_gen = None
        self._pool_shutdown = None
        self._sidecar_ports = None
        self._seen_gen = 0
        #: monotone hot-swap counter, bumped on every successful _load
        #: (deploy/reload/undeploy-reload) — the rollout controller's
        #: GET /deploy.json witness that a generation actually flipped
        self._swap_generation = 0
        #: set via attach_server(); when present, /undeploy also stops the
        #: HTTP server shortly after responding (reference parity: `pio
        #: undeploy` terminates the server process, not just the flag)
        self._server = None
        self._load(instance_id)
        window_us = knobs.knob_float("PIO_TPU_SERVE_MICROBATCH_US")
        adaptive = knobs.knob_str(
            "PIO_TPU_SERVE_MICROBATCH_ADAPTIVE"
        ) != "0"
        self._batcher = (
            _MicroBatcher(self, window_us / 1e6, adaptive=adaptive)
            if window_us > 0 else None
        )

        self.router = Router()
        r = self.router
        r.add("GET", "/", self.status)
        r.add("POST", "/queries\\.json", self.query)
        r.add("GET", "/stats\\.json", self.get_stats)
        r.add("GET", "/device\\.json", self.get_device)
        r.add("GET", "/metrics", self.get_metrics)
        r.add("GET", "/traces\\.json", self.get_traces)
        r.add("GET", "/logs\\.json", self.get_logs)
        r.add("GET", "/slo\\.json", self.get_slo)
        r.add("GET", "/qos\\.json", self.get_qos)
        r.add("GET", "/faults\\.json", self.get_faults)
        r.add("GET", "/debug/hotpath\\.json", self.get_hotpath)
        r.add("GET", "/debug/profile\\.json", self.get_profile)
        r.add("POST", "/debug/profile\\.json", self.post_profile)
        r.add("GET", "/healthz", self.healthz)
        r.add("GET", "/readyz", self.readyz)
        r.add("POST", "/reload", self.reload)
        r.add("POST", "/deploy\\.json", self.deploy_verified)
        r.add("GET", "/deploy\\.json", self.deploy_report)
        r.add("POST", "/undeploy", self.undeploy)
        r.add("GET", "/plugins\\.json", self.list_plugins)

    # -- engine/model lifecycle --------------------------------------------
    def _load(self, instance_id: Optional[str]) -> None:
        engine, engine_params = build_engine(self.variant)
        instance_id = resolve_instance_id(self.variant, instance_id)
        models = load_models_for_instance(
            instance_id, engine, engine_params, self.ctx,
            variant=self.variant,
        )
        # mesh attach must precede prepare_for_serving (inside
        # algorithms_with_models): templates warm their device scorer
        # there, and a model that only fits sharded would fail the
        # per-device budget on the single-chip path
        serve_mesh = self._serving_mesh()
        if serve_mesh is not None:
            for m in models:
                try:
                    m.__dict__["_serve_mesh"] = serve_mesh
                except AttributeError:  # __slots__ model: no mesh channel
                    pass
        pairs = engine.algorithms_with_models(engine_params, models)
        serving = engine.make_serving(engine_params)
        # resolve once at load — a conflicting query-class config should fail
        # deploy/reload, not the first query
        query_class = resolve_query_class(pairs)
        # resident placement + bucket warmup run on the INCOMING pairs
        # before the swap is visible: on a /reload the old model keeps
        # serving while the new generation's params cross the link and
        # its shape buckets compile, then the swap installs model +
        # warmed set + resident scorers atomically (hot-swap = eviction
        # of the old generation's entries AND retirement of its device
        # params)
        sharding_info = self._place_mesh(pairs)
        incoming = self._place_resident(pairs)
        warmed = self._warm_buckets(pairs, serving)
        eng = self.variant.engine_id
        with self._swap_lock:
            self._sharding_info = sharding_info
            self.engine, self.engine_params = engine, engine_params
            self.instance_id = instance_id
            self._swap_generation += 1
            self.pairs, self.serving = pairs, serving
            self.query_class = query_class
            if self._buckets.warmed:
                self._bucket_evictions_total.inc(engine_id=eng)
            gen = self._buckets.install(warmed)
            self._bucket_entries.set(len(warmed), engine_id=eng)
            outgoing, self._resident = self._resident, incoming
        # retire OUTSIDE the lock: an in-flight dispatch that already
        # read the old scorer finishes against still-live params, then
        # every later read sees `retired` and falls back to the freshly
        # swapped host mirror — stale weights can never answer
        for sc in outgoing:
            sc.retire()
        self._resident_params_bytes.set(
            sum(sc.placed_bytes for sc in incoming), engine_id=eng
        )
        self._resident_models.set(len(incoming), engine_id=eng)
        # stamp the generation the new placements went live under — the
        # /device.json placement table keys eviction decisions by it
        self.devwatch.set_generation(gen)
        log.info(
            "serving engine instance %s (generation %d, %d resident)",
            instance_id, gen, len(incoming),
        )

    def _serving_mesh(self):
        """The mesh to shard serving params over, or None.

        Gate: ``PIO_TPU_MESH_SERVE=1`` enables sharded serving over the
        context mesh; ``0``/unset keeps the single-device placement every
        existing deploy runs (sharding changes device placement, so it is
        opt-in per server, not inferred from mesh presence)."""
        flag = knobs.knob_str("PIO_TPU_MESH_SERVE").strip().lower()
        if flag not in ("1", "on", "true"):
            return None
        mesh = self.ctx.mesh
        if mesh is None or self.ctx.num_devices <= 1:
            return None
        return mesh

    def _place_mesh(self, pairs) -> Optional[dict]:
        """Shard each model's serving factor tables over the serving mesh
        (partition-rule placement inside the scorer; see ops/topn.py).

        Runs on the INCOMING pairs before the swap, like residency: the
        scorers build eagerly here so placement cost and failures land at
        deploy, not inside the first live query. A model whose placement
        fails (budget, shapes) serves single-device instead — counted by
        ``pio_tpu_shard_gather_fallback_total``."""
        # the incoming generation's sharded footprint replaces the old
        # one wholesale (placements rebuild below)
        self.devwatch.ledger_clear("shard")
        mesh = self._serving_mesh()
        if mesh is None:
            return None
        eng = self.variant.engine_id
        placed = []
        for algo, m in pairs:
            # resident scorers read the same attribute at build time;
            # __dict__ write keeps frozen dataclass models settable
            try:
                m.__dict__["_serve_mesh"] = mesh
            except AttributeError:  # __slots__ model: no mesh channel
                continue
            if not hasattr(m, "scorer"):
                continue
            try:
                failpoint("shard.place")
                # prepare_for_serving usually built the sharded scorer
                # already (the mesh attaches before it in _load); rebuild
                # only when the cache predates the mesh or went host-mode
                sc = m.__dict__.get("_scorer")
                if sc is None or not getattr(sc, "mesh_sharded", False):
                    m.__dict__.pop("_scorer", None)
                    sc = m.scorer(warmup=True)
                info = sc.sharding_info() if sc is not None else None
            except Exception:
                log.exception(
                    "mesh placement failed for %s; serving single-device",
                    type(m).__name__,
                )
                m.__dict__.pop("_serve_mesh", None)
                m.__dict__.pop("_scorer", None)
                self._shard_gather_fallback_total.inc(engine_id=eng)
                continue
            if info is None:
                # scorer chose the host/replicated path (budget, 1-chip
                # mesh, host-forced mode): not a sharded placement
                self._shard_gather_fallback_total.inc(engine_id=eng)
                continue
            info = dict(info)
            info["model"] = type(m).__name__
            placed.append(info)
            # ledger: each chip holds bytesPerDevice of this model
            # (symmetric placement — device 0 stands for the set)
            self.devwatch.ledger_place(
                "shard", type(m).__name__,
                int(info["bytesPerDevice"]),
                name=f"sharded {type(m).__name__}",
            )
            self._shard_bytes_placed_total.inc(
                int(info["totalBytes"]), engine_id=eng
            )
            log.info(
                "sharded placement: %s over %d device(s), %d B/device",
                type(m).__name__, info["nDevices"], info["bytesPerDevice"],
            )
        return {
            "enabled": True,
            "meshDevices": self.ctx.num_devices,
            "models": placed,
        }

    def _place_resident(self, pairs) -> list:
        """Build + place device-resident scorers for the incoming pairs
        (``PIO_TPU_DEVICE_RESIDENT`` gate — see server/residency.py).
        Each scorer is attached to its model as ``_resident`` so the
        algorithm's predict/batch_predict dispatch through the device
        params; a template without a scorer (or a build failure) keeps
        its host-mirror path."""
        from pio_tpu.server import residency

        if not residency.enabled():
            return []
        eng = self.variant.engine_id

        # bound cells: these callbacks run inside every score_wire
        # dispatch — per-call labels() resolution is measurable there
        h2d_cell = self._h2d_bytes_total.labels(eng)
        donation_cells = {
            outcome: self._donation_total.labels(eng, outcome)
            for outcome in ("hit", "miss")
        }

        def on_h2d(nbytes: int) -> None:
            h2d_cell.inc(float(nbytes))

        def on_donation(outcome: str) -> None:
            donation_cells[outcome].inc()

        placed = []
        for algo, m in pairs:
            try:
                sc = algo.resident_scorer(m)
            except Exception:
                log.exception(
                    "resident_scorer failed for %s; model serves from "
                    "the host mirror", type(algo).__name__,
                )
                continue
            if sc is None:
                continue
            sc.bind(on_h2d=on_h2d, on_donation=on_donation)
            sc.prealloc(self._buckets.buckets)
            m._resident = sc
            placed.append(sc)
            log.info(
                "resident scorer %r placed: %d param bytes, wire=%s",
                sc.name, sc.placed_bytes, sc.wire,
            )
        return placed

    def _bucket_warm_enabled(self) -> bool:
        """Warm the bucket ladder only where batched dispatches can
        actually happen — a micro-batching server or a batch-lane device
        worker. A plain per-request deploy (most tests, `pio deploy`
        without the env) must not pay len(buckets) compiles at boot.
        ``PIO_TPU_BUCKET_WARMUP=0`` force-disables, ``=1``
        force-enables."""
        flag = knobs.knob_str("PIO_TPU_BUCKET_WARMUP")
        if flag == "0":
            return False
        if flag == "1":
            return True
        if knobs.knob_float("PIO_TPU_SERVE_MICROBATCH_US") > 0:
            return True
        return self._lane_drainer is not None

    def _warm_buckets(self, pairs, serving) -> list:
        """Compile the bucket ladder for ``pairs`` by dispatching each
        bucket once with a representative query (``algo.warmup_query``).
        Returns the warmed bucket list — empty when warmup is disabled
        or no algorithm can mint a warmup query (the ladder then warms
        lazily on first live dispatch, counted as retraces)."""
        if not self._bucket_warm_enabled() or not pairs:
            return []
        wq = None
        for algo, m in pairs:
            try:
                wq = algo.warmup_query(m)
            except Exception:
                log.exception(
                    "warmup_query failed for %s", type(algo).__name__
                )
            if wq is not None:
                break
        if wq is None:
            log.info(
                "no algorithm provided a warmup query; shape buckets "
                "warm lazily on first dispatch"
            )
            return []
        t0 = monotonic_s()
        warmed = []
        for b in self._buckets.buckets:
            try:
                # compile attribution: each bucket's first sweep is the
                # trace+compile; a hot-swap re-warm over an unchanged
                # ladder hits the jit cache and is NOT recounted
                with self.devwatch.span("bucket_warmup", key=("bucket", b)):
                    self._run_batch(pairs, serving, [wq] * b)
                warmed.append(b)
            except Exception:
                log.exception("bucket %d warmup dispatch failed", b)
                break
        log.info(
            "bucket warmup: compiled buckets %s in %.0f ms",
            warmed, (monotonic_s() - t0) * 1e3,
        )
        return warmed

    # -- handlers -----------------------------------------------------------
    def status(self, req: Request):
        self._pool_sync()
        return 200, {
            "status": "deployed" if self._deployed else "undeployed",
            "engineId": self.variant.engine_id,
            "engineFactory": self.variant.engine_factory,
            "engineInstanceId": self.instance_id,
            "startTime": self.start_time.isoformat(),
            "requestCount": self.stats.count,
        }

    # -- health/readiness (ISSUE 2) -----------------------------------------
    def _http_loop_alive(self):
        """Liveness: the attached server's accept-loop thread. When the
        server runs ``serve_forever`` in the main thread (or none is
        attached — embedded use), there is no thread to check: pass."""
        server = self._server
        t = getattr(server, "_thread", None) if server is not None else None
        if t is None:
            return True, "accept loop not thread-managed"
        return t.is_alive(), "accept loop thread " + (
            "alive" if t.is_alive() else "dead"
        )

    def _check_engine_ready(self):
        with self._swap_lock:
            ok = self._deployed and bool(self.pairs)
            iid = self.instance_id
        if not self._deployed:
            return False, "undeployed"
        return ok, f"instance {iid}" if ok else "no algorithms loaded"

    def _check_storage_ready(self):
        """Readiness: the metadata store must answer, and the deployed
        instance must still exist there (a vanished record means /reload
        can never succeed)."""
        rec = Storage.get_meta_data_engine_instances().get(self.instance_id)
        if rec is None:
            return False, f"instance {self.instance_id} not in metadata store"
        return True, "metadata store reachable"

    def _availability_good_total(self):
        eng = self.variant.engine_id
        total = self._queries_total.value(eng)
        errors = self._query_errors_total.value(eng)
        return total - errors, total

    def healthz(self, req: Request):
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request):
        ok, report = self.health.readiness()
        return (200 if ok else 503), report

    def get_logs(self, req: Request):
        """Recent structured log entries from the in-process ring,
        filterable by minimum level and exact trace id."""
        n = int_param(req.params, "n", 100, lo=0, hi=slog.ring().cap)
        try:
            return 200, slog.logs_payload(
                n=n,
                level=req.params.get("level"),
                trace_id=req.params.get("trace_id"),
                logger=req.params.get("logger"),
            )
        except ValueError as e:
            raise HTTPError(400, str(e))

    def get_slo(self, req: Request):
        """Burn-rate evaluation of the configured SLOs against the live
        counters/histograms (empty when none were declared)."""
        if self.slo is None:
            return 200, {"slos": [], "configured": False}
        out = self.slo.evaluate()
        out["configured"] = True
        return 200, out

    def get_qos(self, req: Request):
        """Admission-control state: policy, bucket level, inflight/queue,
        shed counts by reason, breaker states, stale-cache stats."""
        if self.qos is None:
            return 200, {"enabled": False}
        return 200, self.qos.snapshot()

    def get_faults(self, req: Request):
        """Armed failpoints + trigger counts (pio_tpu.faults)."""
        from pio_tpu import faults

        return 200, faults.snapshot()

    def _shed(self, req: Request, reason: str, retry_after_s: float):
        """Turn a shed decision into a response: a stale-cache hit (when
        degradation is configured) answers 200 with ``X-Pio-Degraded``;
        otherwise 429 (rate limits) / 503 (everything else) with
        ``Retry-After``. ``pio_tpu_qos_shed_total`` counts only the
        actual rejections — degraded serves get their own counter."""
        if self.qos.stale is not None and req.body is not None:
            cached = self.qos.stale.get(cache_key(req.body))
            if cached is not None:
                self.qos.count_degraded()
                return 200, json_response(
                    cached, {DEGRADED_HEADER: DEGRADED_VALUE}
                )
        self.qos.count_shed(reason)
        status = 429 if reason in ("rate_limit", "key_rate_limit") else 503
        raise HTTPError(
            status, f"overloaded: {reason}",
            headers=retry_after_header(retry_after_s),
        )

    def _parse_query(self, body: Any, qc):
        if body is None:
            raise HTTPError(400, "query body required")
        if not isinstance(body, dict):
            raise HTTPError(400, "query body must be a JSON object")
        if qc is None:
            return body  # raw dict queries
        try:
            return params_from_dict(qc, body)
        except ParamsError as e:
            raise HTTPError(400, str(e))

    def list_plugins(self, req: Request):
        from pio_tpu.server.plugins import installed_plugins

        return 200, installed_plugins()

    def _slow_threshold_s(self) -> Optional[float]:
        """The slow-trace capture threshold in seconds, or None while
        there is no basis for one (fresh server, no SLO declared)."""
        ms = knobs.knob_float("PIO_TPU_SLOW_TRACE_MS")
        if ms > 0:
            return ms / 1e3
        slo = self.slo
        if slo is not None:
            thresholds = [
                o.threshold_s for o in slo.objectives
                if o.kind == "latency" and o.threshold_s
            ]
            if thresholds:
                return min(thresholds)
        # no declared objective: estimate p99 from the live distribution
        # once it has enough mass to mean something
        cell = self._e2e_cell
        if cell.count >= 64:
            return cell.quantile(0.99, pool=False)
        return None

    def get_hotpath(self, req: Request):
        """Per-stage latency budget (count/avg/p50/p95 + attributed
        fraction of the end-to-end average). ``?pool=0`` restricts a
        pool worker's answer to its own stripe."""
        pool = req.params.get("pool", "1") != "0"
        return 200, hotpath_payload(
            self.tracer, self._e2e_cell,
            stage_order=QUERY_STAGES + QUERY_SUBSTAGES, pool=pool,
            slow_threshold_s=self._slow_threshold_s(),
        )

    def get_profile(self, req: Request):
        """Device-profiler hook status (captures, armed, directory)."""
        return 200, self.profile_hook.to_dict()

    def post_profile(self, req: Request):
        """``?restart=1`` re-arms the first-N device-execution profiler
        for another capture window (admin-gated: profiling taxes the hot
        path and writes server-side files)."""
        self._check_admin(req)
        if req.params.get("restart") in ("1", "true"):
            n = int_param(req.params, "n", 0, lo=0)
            return 200, self.profile_hook.restart(n)
        return 200, self.profile_hook.to_dict()

    def enable_pool(self, idx: int, size: int, gen, shutdown_evt,
                    metrics_path: Optional[str] = None,
                    sidecar_ports=None) -> None:
        """Wire this worker into a serving pool: ``gen`` is a shared
        multiprocessing generation counter (a /reload on ANY worker bumps
        it; the others lazily reload before their next query), and
        ``shutdown_evt`` a shared event that /undeploy sets so the
        supervisor brings the whole pool down.

        ``metrics_path`` points at the supervisor-created shared-memory
        metrics segment; binding it makes ``GET /metrics`` on THIS worker
        report pool-wide sums (the kernel balances scrape connections
        across workers just like queries — without aggregation every
        scrape would see 1/size of the traffic)."""
        self._pool_idx = idx
        self._pool_size = size
        self._pool_gen = gen
        self._pool_shutdown = shutdown_evt
        self._seen_gen = gen.value
        #: loopback sidecar ports of EVERY pool worker (shared array,
        #: published as each worker's sidecar comes up) — the fan-out
        #: path that lets /traces.json merge all workers' private rings
        self._sidecar_ports = sidecar_ports
        # pool-mode probes: worker main loop beats the heartbeat; the
        # supervisor's /healthz poll catches a wedged loop. Readiness
        # additionally requires the shared metrics stripe (without it
        # this worker silently under-reports every pool-wide scrape).
        slog.set_worker(str(idx))
        # pool-unique trace ids (query-w2-17): SO_REUSEPORT workers would
        # otherwise mint colliding ids, making the merged view ambiguous
        self.tracer.set_worker(idx)
        self.health.add_liveness("event_loop", self.heartbeat.check)
        self.health.add_readiness("pool_stripe", self._check_pool_stripe)
        if metrics_path:
            from pio_tpu.obs.shm import PoolMetricsSegment

            try:
                seg = PoolMetricsSegment.open(metrics_path)
                self.obs.bind_pool_segment(seg, idx)
                # stripe generation export (ISSUE 11): the supervisor
                # bumps the segment word at every (re)spawn and negates
                # it at retirement; re-reading at scrape time lets
                # aggregators tell stripe adoption (counter
                # discontinuity) from traffic and spot retired stripes
                # whose retained totals will never move again
                self.obs.add_collector(
                    lambda: _stripe_generation_lines(seg)
                )
                if self.qos is not None:
                    # the admitted-counter stripes are live now; forget
                    # pre-bind totals so history doesn't drain the bucket
                    self.qos.on_pool_bound()
            except Exception:
                log.exception(
                    "pool metrics segment bind failed; this worker "
                    "exposes local-only metrics"
                )

    def _check_pool_stripe(self):
        if self.obs.pool_bound:
            return True, f"stripe {self._pool_idx} bound"
        return False, "shared metrics segment not bound"

    def enable_batch_lane(self, path: str, doorbell, resp_events,
                          device: bool) -> None:
        """Wire this pool worker into the cross-worker batch lane.

        The DEVICE worker opens the segment and runs the drainer thread
        (aggregating every stripe into one bucketed dispatch); every
        other worker gets a :class:`LaneClient` and ships its query
        bodies over instead of dispatching locally — batch occupancy
        scales with pool size instead of fragmenting per process."""
        eng = self.variant.engine_id
        try:
            seg = BatchLaneSegment.open(path)
        except Exception:
            log.exception(
                "batch lane segment open failed; worker %s serves "
                "locally", self._pool_idx,
            )
            return
        self._lane_seg = seg
        if device:
            def on_drain(n: int, batches: int) -> None:
                self._lane_drained_total.inc(n, engine_id=eng)
                self._lane_batches_total.inc(batches, engine_id=eng)
                self._lane_depth.set(seg.pending_depth(), engine_id=eng)

            self._lane_drainer = LaneDrainer(
                seg, self._lane_dispatch, doorbell, resp_events,
                on_drain=on_drain,
            ).start()
            self.health.add_liveness(
                "batch_lane", lambda: (
                    (True, "drainer alive")
                    if self._lane_drainer.thread is not None
                    and self._lane_drainer.thread.is_alive()
                    else (False, "drainer thread dead")
                ),
            )
            # the device worker now has batched dispatches to absorb:
            # warm the ladder if deploy happened before the lane came up
            if not self._buckets.warmed and self._deployed:
                with self._swap_lock:
                    pairs, serving = self.pairs, self.serving
                warmed = self._warm_buckets(pairs, serving)
                if warmed:
                    self._buckets.install(warmed)
                    self._bucket_entries.set(len(warmed), engine_id=eng)
            log.info("batch lane drainer up (device worker)")
        else:
            self._lane_client = LaneClient(
                seg, self._pool_idx, doorbell,
                resp_events[self._pool_idx],
            )
            log.info("batch lane client up (worker %s)", self._pool_idx)

    def _lane_dispatch(self, bodies: list) -> list:
        """Drainer-side service: parse each shipped body with THIS
        worker's snapshot and serve the whole cycle as one bucketed
        batch. Runs on the drainer thread — sync the pool generation
        first so a /reload elsewhere is honored here too.

        A body is either a JSON query body or a :class:`PackedQuery`
        (int8 lane wire): packed features dequantize with this worker's
        resident scales — identical to the submitter's, both came off
        the same trained model — so the rebuilt query re-quantizes to
        the exact codes that crossed the ring."""
        self._pool_sync()
        with self._swap_lock:
            qc = self.query_class
            serving = self.serving
            resident = list(self._resident)
        sc = resident[0] if len(resident) == 1 else None

        def to_query(b):
            if isinstance(b, PackedQuery):
                if sc is None or sc.scales is None \
                        or sc.query_factory is None:
                    raise ValueError(
                        "packed lane query but no resident int8 scorer "
                        "on the device worker"
                    )
                return sc.query_factory(sc.dequantize(b.codes))
            return self._parse_query(b, qc)

        queries = [serving.supplement(to_query(b)) for b in bodies]
        results, _fresh = self._predict_batch_bucketed(queries)
        return [_to_jsonable(r) for r in results]

    def _lane_pack(self, query) -> Optional[bytes]:
        """Wire-encode ``query`` as a packed int8 lane frame, or None to
        ship the JSON body. Packing is sound only when exactly ONE
        resident scorer serves on the int8 wire (the drainer dequantizes
        with the same training scales, making the round trip exact) and
        the query carries a dense feature vector."""
        resident = self._resident
        if len(resident) != 1:
            return None
        sc = resident[0]
        if sc.wire != "int8" or sc.retired or sc.query_factory is None:
            return None
        vec = getattr(query, "vector", None)
        if vec is None:
            return None
        try:
            return pack_query_i8(sc.quantize(vec(sc.in_dim))[0])
        except Exception:
            return None

    def _pool_sync(self) -> None:
        gen = self._pool_gen
        if gen is not None and gen.value != self._seen_gen:
            target = gen.value
            # mark the generation consumed only AFTER a successful load —
            # a transient reload failure must be retried on the next
            # query, not leave this worker on the stale model forever
            self._load(None)
            self._seen_gen = target

    def query(self, req: Request):  # pio: hotpath
        if not self._deployed:
            raise HTTPError(503, "undeployed")
        if req.packed is not None:
            # packed binary wire (PACKED_QUERY_CONTENT_TYPE): the body
            # never meets the JSON codec — hand the frame view to the
            # zero-copy path
            return self._query_packed(req)
        self._pool_sync()
        t0 = monotonic_s()
        error = True
        eng = self.variant.engine_id
        adm = None
        deadline = None
        bcall = None
        trace_id = None
        # cross-process propagation: adopt the caller's trace id (and the
        # span that issued the call) so one id names the whole waterfall
        in_tid, in_parent = parse_trace_header(req.header(TRACE_HEADER))
        try:
            if self.qos is not None:
                # deadline clock starts at receipt; a malformed header is
                # a client error, not silently "no deadline"
                try:
                    deadline = Deadline.from_header(
                        req.header(DEADLINE_HEADER),
                        default_ms=self.qos.policy.deadline_ms,
                    )
                except ValueError as e:
                    raise HTTPError(400, str(e))
                timeout_s = (
                    max(deadline.remaining_s(), 0.0)
                    if deadline is not None else None
                )
                adm = self.qos.admit(
                    priority=req.header(PRIORITY_HEADER),
                    timeout_s=timeout_s,
                )
                if not adm.ok:
                    out = self._shed(req, adm.reason, adm.retry_after_s)
                    error = False
                    return out
                if self._scorer_breaker is not None:
                    bcall = self._scorer_breaker.acquire()
                    if not bcall.allowed:
                        out = self._shed(req, "breaker", bcall.retry_after_s)
                        error = False
                        return out
            t_admitted = monotonic_s()
            with self.tracer.trace(
                "query", trace_id=in_tid, parent=in_parent
            ) as tr:
                trace_id = tr.trace_id
                # the trace opens only AFTER admission, but the request
                # began at socket read: rebase so the waterfall shows
                # accept at offset 0 instead of pretending the request
                # started at parse
                pre_s = req.read_s + (t_admitted - t0)
                tr.rebase(pre_s)
                tr.add_span("accept", req.read_s, rel_start_s=0.0)
                # admit runs from read-end to NOW (not to t_admitted):
                # the trace-open and rebase work just done is request
                # time, and end-aligning the span to the parse start
                # keeps the top-level stages tiling without overlap
                if adm is not None and adm.queue_wait_s > 0:
                    # time blocked in the concurrency limiter's queue —
                    # the tail end of the admit window
                    tr.add_span(
                        "admit.queue", adm.queue_wait_s,
                        rel_start_s=max(pre_s - adm.queue_wait_s, 0.0),
                    )
                rel_admit_end = tr.elapsed_s
                tr.add_span(
                    "admit", rel_admit_end - req.read_s,
                    rel_start_s=req.read_s,
                )
                # one consistent snapshot — a concurrent /reload must
                # not mix the old engine's query class with the new
                # engine's models. (The micro-batch path re-snapshots
                # in the worker; the batch is served from that
                # snapshot.) Inside the parse span: swap-lock wait is
                # request preparation time, and leaving it between
                # spans would leak it from the budget.
                with self._swap_lock:
                    pairs, serving, qc = (
                        self.pairs, self.serving, self.query_class
                    )
                query = self._parse_query(req.body, qc)
                query = serving.supplement(query)
                rel_parse_end = tr.elapsed_s
                tr.add_span(
                    "parse", rel_parse_end - rel_admit_end,
                    rel_start_s=rel_admit_end,
                )
                try:
                    if deadline is not None and deadline.expired():
                        # budget burned before execution (queue wait /
                        # parse) — shed before the model runs
                        raise DeadlineExceeded("deadline elapsed")
                    if self._lane_client is not None:
                        # cross-worker batch lane: ship the raw query
                        # body to the device worker (it re-parses with
                        # its own snapshot), block on the response cell.
                        # Any lane trouble falls back to local solo
                        # dispatch — the lane is an optimization, never
                        # a correctness dependency.
                        rel_exec = tr.elapsed_s
                        tr.add_span(
                            "queue", rel_exec - rel_parse_end,
                            rel_start_s=rel_parse_end,
                        )
                        timeout_s = None
                        if deadline is not None:
                            timeout_s = max(
                                0.005,
                                min(self._lane_client.timeout_s,
                                    deadline.remaining_s() - 0.01),
                            )
                        try:
                            result = self._lane_client.submit(
                                req.body, timeout_s=timeout_s,
                                packed=self._lane_pack(query),
                            )
                            self._lane_enqueued_total.inc(engine_id=eng)
                        except LaneFallback as lf:
                            self._lane_fallback_total.inc(
                                engine_id=eng, reason=lf.reason
                            )
                            if lf.reason == "full":
                                self._lane_full_total.inc(engine_id=eng)
                            result = self._predict_one(query)
                        tr.add_span(
                            "execute", tr.elapsed_s - rel_exec,
                            rel_start_s=rel_exec,
                        )
                    elif self._batcher is not None \
                            and self._batcher.active() \
                            and not self._evloop_front:
                        # (bypassed on the evloop front: submit parks
                        # the calling thread for the batch window, and
                        # that thread IS the event loop)
                        result = self._batcher.submit(
                            query, span_sink=tr, deadline=deadline
                        )
                    else:
                        # no batcher: "queue" is just the pre-dispatch
                        # bookkeeping (deadline check) between parse end
                        # and execute start — end-aligned so the stages
                        # tile with no gap in the hotpath budget
                        rel_exec = tr.elapsed_s
                        tr.add_span(
                            "queue", rel_exec - rel_parse_end,
                            rel_start_s=rel_parse_end,
                        )
                        t_dev = monotonic_s()
                        with self.profile_hook.capture():
                            predictions = [
                                algo.predict(m, query)
                                for algo, m in pairs
                            ]
                        tr.add_span(
                            "execute.device", monotonic_s() - t_dev
                        )
                        result = serving.serve(query, predictions)
                        tr.add_span(
                            "execute", tr.elapsed_s - rel_exec,
                            rel_start_s=rel_exec,
                        )
                except DeadlineExceeded:
                    out = self._shed(req, "deadline", 0.0)
                    error = False
                    return out
                except HTTPError:
                    raise
                except Exception:
                    if bcall is not None:
                        bcall.failure()
                    raise
                rel_ser = tr.elapsed_s
                if bcall is not None:
                    bcall.success()
                out = _to_jsonable(result)
                for blocker in QUERY_BLOCKERS:
                    try:
                        # output blockers see (query, prediction) and
                        # veto the response with ValueError → client 400
                        blocker(req.body, out)
                    except ValueError as e:
                        raise HTTPError(400, str(e))
                pr_id = None
                if self.feedback:
                    pr_id = uuid.uuid4().hex
                    if isinstance(out, dict):
                        out = {**out, "prId": pr_id}
                    self._log_feedback(req.body, out, pr_id)
                for sniffer in QUERY_SNIFFERS:
                    try:
                        sniffer(req.body, out)
                    except Exception:
                        log.exception("query sniffer failed")
                if self.qos is not None and self.qos.stale is not None \
                        and req.body is not None:
                    # feed the degradation cache with the fresh answer
                    self.qos.stale.put(cache_key(req.body), out)
                error = False
                # inside the trace → this record carries the trace id,
                # joining /logs.json?trace_id=... to /traces.json
                log.info(
                    "served query engine=%s ms=%.3f", eng,
                    (monotonic_s() - t0) * 1e3,
                )
                # serialize covers everything between the model result
                # and handing the response to the writer — JSON
                # conversion, blockers/sniffers, the stale-cache feed
                # and the served-query log line — end-aligned so it
                # tiles flush against both execute and write. The same
                # mark anchors the write span at HANDLER completion,
                # not at the socket write: the return path between them
                # (router unwind, the finally block's accounting) is
                # real request time, and leaving it between spans would
                # break the tiling the hotpath budget sums over
                rel_done_s = tr.elapsed_s
                tr.add_span(
                    "serialize", rel_done_s - rel_ser,
                    rel_start_s=rel_ser,
                )

                def _written(write_s: float, _tr=tr, _rel=rel_done_s):
                    # fires after the response bytes hit the socket: the
                    # last stage of the waterfall, and the only moment
                    # the TRUE end-to-end latency (accept→write) exists.
                    # ONE clock read for both: a second elapsed_s after
                    # the span observe would put the observe's own cost
                    # into e2e but no stage, eroding attribution
                    done_s = _tr.elapsed_s
                    _tr.add_span(
                        "write", done_s - _rel, rel_start_s=_rel
                    )
                    _tr.extend_total()
                    self._e2e_cell.observe(
                        done_s, exemplar=_tr.trace_id
                    )

                req.on_written = _written
                # echo the id so an untraced caller learns which trace
                # its request minted (and a traced one confirms adoption)
                return 200, json_response(
                    out, {TRACE_HEADER: tr.trace_id}
                )
        finally:
            if bcall is not None:
                # exits that never reached the scorer (parse 400,
                # deadline shed, undeployed 503) must still release a
                # half-open probe grant or the breaker wedges in
                # HALF_OPEN with all grants leaked; no-op after
                # success()/failure()
                bcall.cancel()
            if adm is not None:
                adm.release()
            dur_s = monotonic_s() - t0
            self.stats.record(dur_s * 1e3, error)
            self._request_cell.observe(dur_s, exemplar=trace_id)
            self._queries_total.inc(engine_id=eng)
            if error:
                self._query_errors_total.inc(engine_id=eng)

    def _query_packed(self, req: Request):  # pio: hotpath=zerocopy
        """Packed int8 query path: the body bytes the HTTP front read
        off the socket ARE the lane frame — validated structurally,
        admitted through the same QoS gate as JSON queries, and written
        straight into the shm ring slot by ``LaneClient.submit_packed``.
        The device worker's response comes back as ready JSON bytes and
        is returned without re-decoding. No JSON codec, no intermediate
        dict, no ``bytes()`` copies anywhere on this path — the
        ``hotpath-zero-copy`` rule proves it from this root.

        Span accounting mirrors :meth:`query` (same end-aligned tiling
        over QUERY_STAGES), with "parse" covering only the frame
        validation — which is the point of the fast path."""
        self._pool_sync()  # pio: disable=hotpath-zero-copy
        t0 = monotonic_s()
        error = True
        eng = self.variant.engine_id
        adm = None
        deadline = None
        bcall = None
        trace_id = None
        in_tid, in_parent = parse_trace_header(req.header(TRACE_HEADER))
        try:
            frame = req.packed
            if not packed_frame_ok(frame):
                self._fastpath_cells["invalid"].inc()
                raise HTTPError(400, "malformed packed query frame")
            if self.qos is not None:
                try:
                    deadline = Deadline.from_header(
                        req.header(DEADLINE_HEADER),
                        default_ms=self.qos.policy.deadline_ms,
                    )
                except ValueError as e:
                    raise HTTPError(400, str(e))
                timeout_s = (
                    max(deadline.remaining_s(), 0.0)
                    if deadline is not None else None
                )
                adm = self.qos.admit(
                    priority=req.header(PRIORITY_HEADER),
                    timeout_s=timeout_s,
                )
                if not adm.ok:
                    # no stale-cache key for a binary body: shed is a
                    # plain 429/503 (raised inside _shed)
                    # pio: disable=hotpath-zero-copy
                    out = self._shed(req, adm.reason, adm.retry_after_s)
                    error = False
                    return out
                if self._scorer_breaker is not None:
                    bcall = self._scorer_breaker.acquire()
                    if not bcall.allowed:
                        # pio: disable=hotpath-zero-copy
                        out = self._shed(
                            req, "breaker", bcall.retry_after_s
                        )
                        error = False
                        return out
            t_admitted = monotonic_s()
            with self.tracer.trace(
                "query", trace_id=in_tid, parent=in_parent
            ) as tr:
                trace_id = tr.trace_id
                pre_s = req.read_s + (t_admitted - t0)
                tr.rebase(pre_s)
                tr.add_span("accept", req.read_s, rel_start_s=0.0)
                if adm is not None and adm.queue_wait_s > 0:
                    tr.add_span(
                        "admit.queue", adm.queue_wait_s,
                        rel_start_s=max(pre_s - adm.queue_wait_s, 0.0),
                    )
                rel_admit_end = tr.elapsed_s
                tr.add_span(
                    "admit", rel_admit_end - req.read_s,
                    rel_start_s=req.read_s,
                )
                # "parse" here is only the frame check already done —
                # end-aligned so the stage tiling matches the JSON path
                rel_parse_end = tr.elapsed_s
                tr.add_span(
                    "parse", rel_parse_end - rel_admit_end,
                    rel_start_s=rel_admit_end,
                )
                try:
                    if deadline is not None and deadline.expired():
                        raise DeadlineExceeded("deadline elapsed")
                    rel_exec = tr.elapsed_s
                    tr.add_span(
                        "queue", rel_exec - rel_parse_end,
                        rel_start_s=rel_parse_end,
                    )
                    if self._lane_client is not None:
                        timeout_s = None
                        if deadline is not None:
                            timeout_s = max(
                                0.005,
                                min(self._lane_client.timeout_s,
                                    deadline.remaining_s() - 0.01),
                            )
                        try:
                            resp = self._lane_client.submit_packed(
                                frame, timeout_s=timeout_s
                            )
                            self._lane_enqueued_total.inc(engine_id=eng)
                            self._fastpath_cells["hit"].inc()
                        except LaneFallback as lf:
                            self._lane_fallback_total.inc(
                                engine_id=eng, reason=lf.reason
                            )
                            if lf.reason == "full":
                                self._lane_full_total.inc(engine_id=eng)
                            # pio: disable=hotpath-zero-copy
                            resp = self._query_packed_local(frame)
                    else:
                        # no lane (solo worker): the local fallback
                        # decodes the frame once — off the proven path
                        # pio: disable=hotpath-zero-copy
                        resp = self._query_packed_local(frame)
                    tr.add_span(
                        "execute", tr.elapsed_s - rel_exec,
                        rel_start_s=rel_exec,
                    )
                except DeadlineExceeded:
                    # pio: disable=hotpath-zero-copy
                    out = self._shed(req, "deadline", 0.0)
                    error = False
                    return out
                except HTTPError:
                    raise
                except Exception:
                    if bcall is not None:
                        bcall.failure()
                    raise
                rel_ser = tr.elapsed_s
                if bcall is not None:
                    bcall.success()
                error = False
                log.info(
                    "served packed query engine=%s ms=%.3f", eng,
                    (monotonic_s() - t0) * 1e3,
                )
                rel_done_s = tr.elapsed_s
                tr.add_span(
                    "serialize", rel_done_s - rel_ser,
                    rel_start_s=rel_ser,
                )

                def _written(write_s: float, _tr=tr, _rel=rel_done_s):
                    # one clock read for the span AND e2e (see query())
                    done_s = _tr.elapsed_s
                    _tr.add_span(
                        "write", done_s - _rel, rel_start_s=_rel
                    )
                    _tr.extend_total()
                    self._e2e_cell.observe(
                        done_s, exemplar=_tr.trace_id
                    )

                req.on_written = _written
                return 200, RawResponse(
                    resp,
                    content_type="application/json; charset=UTF-8",
                    headers={TRACE_HEADER: tr.trace_id},
                )
        finally:
            if bcall is not None:
                bcall.cancel()
            if adm is not None:
                adm.release()
            dur_s = monotonic_s() - t0
            self.stats.record(dur_s * 1e3, error)
            self._request_cell.observe(dur_s, exemplar=trace_id)
            self._queries_total.inc(engine_id=eng)
            if error:
                self._query_errors_total.inc(engine_id=eng)

    def _query_packed_local(self, frame) -> bytes:
        """Local fallback for the packed wire (solo worker, or the lane
        shed this request): decode the frame with this worker's resident
        scales and predict solo. The unpack copies the codes once — this
        is the non-zero-copy fallback, deliberately OFF the
        zerocopy-marked path (its call sites are suppressed)."""
        pq = unpack_query_i8(frame)
        with self._swap_lock:
            serving = self.serving
            resident = list(self._resident)
        sc = resident[0] if len(resident) == 1 else None
        if sc is None or sc.scales is None or sc.query_factory is None:
            self._fastpath_cells["unavailable"].inc()
            raise HTTPError(
                400,
                "packed queries need exactly one int8 resident scorer",
            )
        result = None
        if sc.result_factory is not None and not sc.retired:
            # direct wire dispatch: the frame's codes ARE this scorer's
            # wire encoding, so skip dequantize → Query → re-quantize
            # and map the argmax code straight to the template's result
            failpoint("scorer.dispatch.packed")
            try:
                out = sc.score_wire(pq.codes.reshape(1, -1))
                result = sc.result_factory(int(out[0]))
            except RuntimeError:
                # a hot swap retired the scorer mid-dispatch: fall back
                # to the generic path, whose predict re-resolves the
                # resident (or the host mirror the swap installed)
                result = None
        if result is None:
            query = serving.supplement(
                sc.query_factory(sc.dequantize(pq.codes))
            )
            result = self._predict_one(query)
        self._fastpath_cells["local"].inc()
        return json.dumps(_to_jsonable(result)).encode("utf-8")

    def pack_query_body(self, body) -> Optional[bytes]:
        """Encode a JSON-style query body as the packed int8 wire frame
        (``PACKED_QUERY_CONTENT_TYPE``), or None when the deployment
        can't serve packed queries (no single int8 resident scorer).
        Test/bench helper — a real producer packs features client-side
        with the published scales."""
        with self._swap_lock:
            qc = self.query_class
            serving = self.serving
        query = serving.supplement(self._parse_query(body, qc))
        return self._lane_pack(query)

    def _log_feedback(self, query_body, result, pr_id: str):
        """Reference: query server POSTs back to the Event Server with prId;
        in-process we write straight to the event store."""
        if self.feedback_app_id is None:
            return
        try:
            Storage.get_levents().insert(
                Event(
                    event="predict",
                    entity_type="pio_pr",
                    entity_id=pr_id,
                    properties={"query": query_body, "prediction": result},
                    pr_id=pr_id,
                ),
                self.feedback_app_id,
            )
        except Exception:
            log.exception("feedback logging failed")

    def _predict_one(self, query):
        """Per-query predict + serve from one consistent snapshot."""
        failpoint("scorer.dispatch.solo")
        with self._swap_lock:
            pairs, serving = self.pairs, self.serving
        t_dev = monotonic_s()
        with self.profile_hook.capture():
            predictions = [algo.predict(m, query) for algo, m in pairs]
        # lands on whatever trace is active here: the request trace
        # (solo/fallback path) — no-op when called untraced
        add_active_span("execute.device", monotonic_s() - t_dev)
        return serving.serve(query, predictions)

    def _run_batch(self, pairs, serving, queries: list):
        """One ``batch_predict`` dispatch per algorithm over the whole
        (already bucket-shaped) batch, then per-query serving combine."""
        per_algo = []
        t_dev = monotonic_s()
        with self.profile_hook.capture():
            for algo, m in pairs:
                got = dict(algo.batch_predict(m, list(enumerate(queries))))
                per_algo.append([got[i] for i in range(len(queries))])
        # one device observation per BATCH (on the microbatch trace via
        # the active-trace contextvar) — per-member device cost is the
        # amortization the batcher exists to buy, so attributing it once
        # is the honest accounting
        add_active_span("execute.device", monotonic_s() - t_dev)
        return [
            serving.serve(q, [pa[i] for pa in per_algo])
            for i, q in enumerate(queries)
        ]

    def _predict_batch(self, queries: list):
        """Micro-batch dispatch (bucketed); results only."""
        return self._predict_batch_bucketed(queries)[0]

    def _predict_batch_bucketed(self, queries: list):
        """Serve a micro-batch through the shape-bucket cache: chunk to
        the max bucket, pad each chunk up to its bucket (replicating the
        last query — padding rows ride the same compiled program and are
        sliced off), dispatch. Returns ``(results, fresh)`` where
        ``fresh`` is True when any chunk hit a cold bucket — a retrace
        the warmup sweep should have absorbed; the micro-batcher's probe
        discards such samples as compile transients."""
        failpoint("scorer.dispatch.batch")
        eng = self.variant.engine_id
        with self._swap_lock:
            pairs, serving = self.pairs, self.serving

        def on_dispatch(n: int, bucket: int, fresh: bool) -> None:
            self._bucket_dispatch_total.inc(engine_id=eng, bucket=str(bucket))
            self._bucket_occ_cell.observe(n / bucket)
            if fresh:
                self._bucket_retrace_total.inc(engine_id=eng)
                # a live retrace IS a compile the warmup should have
                # absorbed — attribute it (count only; the dispatch
                # isn't individually timed here)
                self.devwatch.record_compile("bucket_dispatch")

        return dispatch_bucketed(
            self._buckets, queries,
            lambda qs: self._run_batch(pairs, serving, qs),
            on_dispatch=on_dispatch,
        )

    def get_stats(self, req: Request):
        window_s = float_param(req.params, "window", 0.0, lo=0.0)
        if window_s > 0:
            out = self.stats.window(window_s)
        else:
            out = self.stats.to_dict()
            stages = self.stage_summary()
            if stages:
                out["stages"] = stages
        if self._batcher is not None:
            out["microbatch"] = self._batcher.to_dict()
        out["buckets"] = self._buckets.to_dict()
        resident = self._resident
        # measuredBytes: backend memory_stats total beside the estimated
        # paramBytes (None on ledger-only backends — the drift gauge
        # covers the live case); device memory can't be split between
        # the residency and sharding placements, so both blocks carry
        # the same device-level measurement
        measured = self.devwatch.measured_bytes()
        out["residency"] = {
            "enabled": bool(resident),
            "paramBytes": sum(sc.placed_bytes for sc in resident),
            "measuredBytes": measured,
            "scorers": [sc.to_dict() for sc in resident],
        }
        with self._swap_lock:
            sharding = self._sharding_info
        out["sharding"] = (
            dict(sharding) if sharding else {"enabled": False}
        )
        if sharding:
            out["sharding"]["measuredBytes"] = measured
        if self._lane_drainer is not None:
            out["batchLane"] = {
                "role": "drainer",
                "cycles": self._lane_drainer.cycles,
                "drained": self._lane_drainer.drained,
                "pendingDepth": self._lane_seg.pending_depth(),
            }
        elif self._lane_client is not None:
            out["batchLane"] = {
                "role": "client",
                "worker": self._pool_idx,
                "timeoutS": self._lane_client.timeout_s,
            }
        if self._pool_idx is not None:
            # pool mode: these are ONE worker's numbers (the kernel
            # balanced this connection here); pool-wide totals live on
            # /metrics (shared-memory aggregation)
            out["worker"] = self._pool_idx
            out["poolSize"] = self._pool_size
            if self.obs.pool_bound:
                out["pool"] = {
                    "requestCount": int(
                        self._queries_total.value(self.variant.engine_id)
                    ),
                    "errorCount": int(
                        self._query_errors_total.value(self.variant.engine_id)
                    ),
                }
        return 200, out

    def get_device(self, req: Request):
        """Device telemetry snapshot (ISSUE 17): per-device bytes
        (measured or ledger-kept), budget headroom, the compile
        attribution table, and placements by serving generation —
        schema in docs/observability.md."""
        return 200, self.devwatch.payload()

    def stage_summary(self) -> dict:
        """Per-stage latency summary from the stage histograms: count,
        mean and interpolated p50/p95/p99 in milliseconds."""
        hist = self.tracer.stage_histogram
        out = {}
        if hist is None:
            return out
        for stage in QUERY_STAGES:
            cell = hist.labels(self.variant.engine_id, stage)
            n = cell.count
            if n <= 0:
                continue
            out[stage] = {
                "count": int(n),
                "avgMs": round(cell.sum / n * 1e3, 3),
                "p50Ms": _q_ms(cell, 0.5),
                "p95Ms": _q_ms(cell, 0.95),
                "p99Ms": _q_ms(cell, 0.99),
            }
        return out

    def _compat_metric_lines(self) -> list:
        """Extra exposition lines kept from the pre-obs server: the
        latency summary (quantile convention) and micro-batch counters —
        existing scrapes and the bench parse these."""
        from pio_tpu.obs import escape_label_value

        s = self.stats.to_dict()
        eng = escape_label_value(self.variant.engine_id)
        lab = f'engine_id="{eng}"'
        lines = []
        if s["avgMs"] is not None:
            lines += [
                "# TYPE pio_tpu_query_latency_ms summary",
                f'pio_tpu_query_latency_ms{{{lab},quantile="0.5"}} '
                f"{s['p50Ms']}",
                f'pio_tpu_query_latency_ms{{{lab},quantile="0.95"}} '
                f"{s['p95Ms']}",
                f'pio_tpu_query_latency_ms{{{lab},quantile="0.99"}} '
                f"{s['p99Ms']}",
                # _sum/_count complete the summary convention so
                # rate(_sum)/rate(_count) windowed averages work
                f"pio_tpu_query_latency_ms_sum{{{lab}}} "
                f"{s['avgMs'] * s['requestCount']}",
                f"pio_tpu_query_latency_ms_count{{{lab}}} "
                f"{s['requestCount']}",
            ]
        if self._batcher is not None:
            mb = self._batcher.to_dict()
            lines += [
                "# TYPE pio_tpu_microbatch_batches_total counter",
                f"pio_tpu_microbatch_batches_total{{{lab}}} {mb['batches']}",
                "# TYPE pio_tpu_microbatch_queries_total counter",
                f"pio_tpu_microbatch_queries_total{{{lab}}} "
                f"{mb['batchedQueries']}",
            ]
        return lines

    def get_metrics(self, req: Request):
        """Prometheus text exposition from the obs registry: request and
        error counters, per-stage latency histograms, plus the legacy
        summary + micro-batch lines via the compat collector. In pool
        mode counters/histograms are POOL-WIDE (shared-memory sums)."""
        return 200, metrics_response(self.obs.render())

    def get_traces(self, req: Request):
        """Recent request traces (ring buffer), slowest first. ``n`` is
        clamped to the ring capacity; negatives/non-ints are a 400.

        ``?slow=1`` serves the tail-capture ring (threshold breaches
        only); ``?id=<trace_id>`` looks up ONE trace across both rings.
        In pool mode every worker holds a private ring, so the answer is
        merged across the pool via each sibling's loopback sidecar;
        ``?local=1`` restricts to this worker (and is what the fan-out
        itself sends, so forwarding cannot recurse)."""
        n = int_param(req.params, "n", 20, lo=0, hi=self.tracer._ring_cap)
        local_only = req.params.get("local") == "1"
        tid = req.params.get("id")
        if tid:
            found = self.tracer.find(tid)
            if found is None and not local_only:
                for t in self._pool_traces(req.params):
                    if t.get("id") == tid:
                        found = t
                        break
            if found is None:
                raise HTTPError(404, f"trace {tid} not in any ring")
            return 200, {"traces": [found]}
        slow = req.params.get("slow") in ("1", "true")
        if slow:
            traces = self.tracer.slow(n)
        else:
            order = req.params.get("order", "slowest")
            traces = self.tracer.recent(n, slowest=(order != "recent"))
        if not local_only:
            siblings = self._pool_traces(req.params)
            if siblings:
                merged = {t["id"]: t for t in traces}
                for t in siblings:
                    merged.setdefault(t.get("id"), t)
                key = (
                    (lambda t: t.get("wallTime") or 0.0)
                    if (not slow and req.params.get("order") == "recent")
                    else (lambda t: t.get("totalMs") or 0.0)
                )
                traces = sorted(
                    merged.values(), key=key, reverse=True
                )[:n]
        return 200, {"traces": traces}

    def _pool_traces(self, params) -> list:
        """Fan ``/traces.json`` out to every SIBLING pool worker's
        loopback sidecar and return their traces (empty outside pool
        mode). The forwarded query carries ``local=1`` so a sibling
        answers from its own ring instead of fanning out again. A worker
        whose sidecar is still coming up (port 0) or mid-restart is
        skipped — a partial merged view beats a 500."""
        ports = self._sidecar_ports
        if ports is None:
            return []
        import json as _json
        from urllib.parse import urlencode
        from urllib.request import urlopen

        fwd = {k: v for k, v in dict(params).items() if k != "local"}
        fwd["local"] = "1"
        qs = urlencode(fwd)
        out = []
        for i in range(len(ports)):
            port = ports[i]
            if i == self._pool_idx or port <= 0:
                continue
            try:
                with urlopen(
                    f"http://127.0.0.1:{port}/traces.json?{qs}",
                    timeout=0.5,
                ) as resp:
                    payload = _json.loads(resp.read().decode("utf-8"))
                out.extend(payload.get("traces", []))
            except Exception:
                continue
        return out

    def _check_admin(self, req: Request):
        if self.admin_key is not None:
            if not keys_equal(req.bearer_key(), self.admin_key):
                raise HTTPError(401, "invalid admin accessKey")
        elif req.client_addr not in ("127.0.0.1", "::1"):
            raise HTTPError(
                403, "admin routes are loopback-only without an admin key"
            )

    def reload(self, req: Request):
        """Hot-swap to the newest COMPLETED instance (reference /reload).

        In pool mode the shared generation counter is bumped, so every
        sibling worker reloads before serving its next query — one admin
        POST rolls the whole pool."""
        self._check_admin(req)
        self._load(None)
        if self._pool_gen is not None:
            with self._pool_gen.get_lock():
                self._pool_gen.value += 1
                self._seen_gen = self._pool_gen.value
        return 200, {"engineInstanceId": self.instance_id}

    def deploy_verified(self, req: Request):
        """Manifest-verified generation swap (the router deploy path).

        The router pushes ``{engineInstanceId, manifest}``; every shard
        record named by the manifest is re-hashed from THIS member's
        store (sha256 + size) before the swap — a mismatch answers 409
        and the current generation keeps serving. Only after
        verification does the instance hot-swap in, exactly like
        /reload (pool siblings follow via the shared generation
        counter, which re-resolves to the latest COMPLETED instance —
        the rollout target in the fabric flow)."""
        from pio_tpu.router.deploy import DeployVerifyError, verify_instance

        self._check_admin(req)
        body = req.body if isinstance(req.body, dict) else {}
        instance_id = body.get("engineInstanceId")
        if not instance_id:
            raise HTTPError(400, "engineInstanceId is required")
        try:
            report = verify_instance(
                Storage.get_model_data_models(),
                instance_id,
                expected=body.get("manifest"),
            )
        except DeployVerifyError as e:
            raise HTTPError(409, f"deploy verification failed: {e}") from e
        self._load(instance_id)
        if self._pool_gen is not None:
            with self._pool_gen.get_lock():
                self._pool_gen.value += 1
                self._seen_gen = self._pool_gen.value
        report["engineInstanceId"] = self.instance_id
        report["verified"] = True
        return 200, report

    def deploy_report(self, req: Request):
        """Generation report (GET /deploy.json): the instance this
        member currently serves, its manifest sha256 set, and the
        monotone swap generation — the rollout controller's incumbent
        discovery and byte-identity witness (a rollback must leave the
        sha set exactly where a rollout found it)."""
        from pio_tpu.router.deploy import load_manifest, manifest_digests

        shas = []
        try:
            manifest = load_manifest(
                Storage.get_model_data_models(), self.instance_id
            )
            if manifest is not None:
                shas = sorted(
                    sha for sha, _size
                    in manifest_digests(manifest).values()
                )
        except Exception:
            pass  # unsharded blob / store hiccup: report without shas
        return 200, {
            "engineInstanceId": self.instance_id,
            "engineId": self.variant.engine_id,
            "manifestSha256": shas,
            "generation": self._swap_generation,
        }

    def undeploy(self, req: Request):
        self._check_admin(req)
        self._deployed = False
        self.devwatch.stop()
        devicewatch.deactivate(self.devwatch)
        if self._batcher is not None:
            self._batcher.stop()
        if self._lane_drainer is not None:
            # answer in-flight lane slots before the workers die so no
            # sibling blocks out its full timeout during teardown
            self._lane_drainer.stop()
            self._lane_drainer = None
        server, shutdown_evt = self._server, self._pool_shutdown

        def _after():
            # fires once the reply is flushed to the socket, so shutdown
            # can never race the client's read (a fixed timer would);
            # stop() runs in its own thread because it blocks until the
            # accept loop exits. In pool mode the shared event tells the
            # supervisor to bring down every sibling worker too.
            if shutdown_evt is not None:
                shutdown_evt.set()
            if server is not None:
                threading.Thread(target=server.stop, daemon=True).start()

        if server is not None or shutdown_evt is not None:
            req.after_response = _after
        return 200, {"message": "undeployed"}

    def attach_server(self, server) -> None:
        """Let /undeploy stop ``server`` (the CLI deploy path attaches;
        embedded servers keep the flag-only behavior unless they opt in)."""
        self._server = server

    def mark_evloop_front(self) -> None:
        """The evloop HTTP front runs handlers inline in its event loop:
        disable the in-process micro-batcher hand-off (its submit parks
        the calling thread for the batch window, and that thread IS the
        loop). Cross-worker batching via the shm lane still applies —
        its submit-side wait is bounded by the lane timeout."""
        self._evloop_front = True


def create_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = 8000,
    instance_id: Optional[str] = None,
    ctx: Optional[ComputeContext] = None,
    feedback: bool = False,
    feedback_app_id: Optional[int] = None,
    admin_key: Optional[str] = None,
    reuse_port: bool = False,
    slos: Optional[List[str]] = None,
    qos: Optional[Any] = None,
) -> Tuple[Any, QueryServerService]:
    from pio_tpu.server.plugins import load_plugins_from_env

    load_plugins_from_env()
    service = QueryServerService(
        variant, instance_id, ctx, feedback, feedback_app_id, admin_key,
        slos=slos, qos=qos,
    )
    front = knobs.knob_str(
        "PIO_TPU_HTTP_FRONT"
    ).strip().lower() or "threaded"
    if front not in ("threaded", "evloop"):
        log.warning(
            "PIO_TPU_HTTP_FRONT=%r is not threaded|evloop; using "
            "threaded", front,
        )
        front = "threaded"
    if front == "evloop" and ssl_context_from_env() is not None:
        # the evloop front has no TLS path: refusing to downgrade the
        # transport silently, serve threaded instead
        log.warning(
            "PIO_TPU_HTTP_FRONT=evloop ignored: TLS is configured and "
            "only the threaded front terminates it"
        )
        front = "threaded"
    if front == "evloop":
        from pio_tpu.server.evfront import EvLoopHTTPServer

        server = EvLoopHTTPServer(
            service.router, host, port, name="pio-tpu-queryserver",
            ssl_context=None, reuse_port=reuse_port,
            registry=service.obs,
        )
        service.mark_evloop_front()
    else:
        server = JsonHTTPServer(
            service.router, host, port, name="pio-tpu-queryserver",
            reuse_port=reuse_port,
        )
    return server, service
