"""SO_REUSEPORT serving pool — N query-server processes on one port.

The reference serves queries from one JVM whose thread pool scales across
cores (``core/.../workflow/CreateServer.scala`` — UNVERIFIED path;
SURVEY.md §2.6 serving-concurrency row). CPython's GIL serializes nearly
all per-request work in one process, so the TPU rebuild's equivalent is a
POOL of worker processes that each bind the same TCP port with
``SO_REUSEPORT``; the kernel load-balances incoming connections across the
listeners, multiplying host-path QPS by the worker count on multi-core
serving hosts.

Accelerator ownership: libtpu admits ONE process per chip. Every pool
worker therefore scores on the **host mirror** of the factor tables (the
deserialized model state — the same adaptive scorer fallback path that
``ops/topn.py`` uses for small batches), with an opt-in for worker 0 to
own the device scorer (``device_worker=True``) when the pool runs on the
TPU VM itself. Non-owner workers pin JAX to CPU before anything imports
it, so they can never grab the chip.

``mesh_worker=True`` is the multi-chip variant of the same ownership
model: worker 0 owns the WHOLE mesh and serves with mesh-sharded factor
tables (``PIO_TPU_MESH_SERVE=1``; partition rules in
``pio_tpu/parallel/partition.py``), so one serving host can hold a model
that exceeds a single chip's memory budget. Siblings stay host-mirror
scorers and route large batches to worker 0 through the batch lane,
exactly as with ``device_worker``.

Pool semantics (shared ``multiprocessing`` primitives, spawn context):

- **/reload** on any worker bumps a shared generation counter after
  reloading itself; every sibling lazily reloads before serving its next
  query — one admin POST rolls the whole pool.
- **/undeploy** on any worker sets a shared shutdown event; the
  supervisor terminates every worker — matching single-process behavior
  where ``pio undeploy`` stops the server.
- **/stats.json** reports per-worker numbers plus ``worker``/``poolSize``
  fields (the kernel decides which worker answers a given connection);
  aggregate across workers client-side or via Prometheus scrapes.

Start one with ``pio deploy --workers N`` or programmatically::

    pool = ServingPool(variant, port=8000, n_workers=4)
    pool.start()
    pool.wait()          # supervise until /undeploy or pool.stop()
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import socket
import tempfile
import time
from typing import Optional

from pio_tpu.utils import knobs
from pio_tpu.obs.metrics import monotonic_s
from pio_tpu.workflow.engine_json import EngineVariant

log = logging.getLogger("pio_tpu.workerpool")

#: respawn budget per worker index AND per kill reason — a worker that
#: keeps dying signals a real fault (bad model, port clash), not a
#: transient, so stop burning processes on it. Budgets are split by
#: reason: a wedge the health sweep shot (``unhealthy``) is usually
#: load-induced and recoverable, so it must not consume the crash
#: budget and retire a worker that never actually crash-looped
_MAX_RESPAWNS = 3
_MAX_RESPAWNS_BY_REASON = {"crash": _MAX_RESPAWNS, "unhealthy": 6}

#: exponential respawn backoff: death N waits base * 2^(N-1), capped — a
#: worker crash-looping on startup (bad model file, import error) must
#: not hot-spin the supervisor through its whole budget in milliseconds
_RESPAWN_BACKOFF_BASE_S = 0.5
_RESPAWN_BACKOFF_CAP_S = 30.0

#: a worker that served this long before dying was not crash-looping:
#: reset its respawn count (and thus its backoff) on death
_RESPAWN_RESET_AFTER_S = 60.0

#: consecutive /healthz failures before the supervisor kills a worker —
#: one failed poll is a blip (GC pause, slow scrape); K in a row on a
#: 1 s-timeout probe is a wedge
_HEALTH_FAILS_TO_KILL = 3


def _worker_main(spec: dict, idx: int, gen, shutdown_evt,
                 health_ports=None, lane_doorbell=None,
                 lane_resp_events=None) -> None:
    """Entry point of one pool worker (spawned process)."""
    owns_device = (
        (spec["device_worker"] or spec.get("mesh_worker")) and idx == 0
    )
    if owns_device and spec.get("mesh_worker"):
        # the mesh owner serves sharded: partition-rule placement over
        # every local device instead of a single-chip upload
        os.environ["PIO_TPU_MESH_SERVE"] = "1"
    if not owns_device:
        # host-mirror scoring only; pin JAX to CPU before ANY import can
        # initialize the TPU runtime (single-owner constraint)
        os.environ["PIO_TPU_SERVE_DEVICE"] = "host"
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:  # jax missing/unconfigurable → host numpy only
            pass

    from pio_tpu.server.http import JsonHTTPServer
    from pio_tpu.server.query_server import create_query_server

    if spec.get("http_front"):
        # uniform front across the pool (see ServingPool._spec): the
        # listener keeps SO_REUSEPORT either way, so evloop means one
        # event loop per worker sharing the same port
        os.environ["PIO_TPU_HTTP_FRONT"] = spec["http_front"]
    variant = EngineVariant(**spec["variant"])
    # a respawn AFTER a pool-wide /reload must join its siblings on the
    # newest COMPLETED instance, not resurrect the originally pinned one
    instance_id = spec.get("instance_id") if gen.value == 0 else None
    server, service = create_query_server(
        variant,
        host=spec["host"],
        port=spec["port"],
        instance_id=instance_id,
        feedback=spec.get("feedback", False),
        feedback_app_id=spec.get("feedback_app_id"),
        admin_key=spec.get("admin_key"),
        reuse_port=True,
        slos=spec.get("slos"),
        qos=spec.get("qos"),
    )
    service.enable_pool(
        idx, spec["n_workers"], gen, shutdown_evt,
        metrics_path=spec.get("metrics_path"),
        sidecar_ports=health_ports,
    )
    if spec.get("lane_path") and lane_doorbell is not None:
        # cross-worker batch lane: worker 0 (the device owner) drains
        # every stripe into one bucketed dispatch; siblings ship their
        # query bodies over shared memory instead of scoring locally
        service.enable_batch_lane(
            spec["lane_path"], lane_doorbell, lane_resp_events,
            device=(idx == 0),
        )
    service.attach_server(server)
    server.start()
    # health sidecar: the pool shares ONE SO_REUSEPORT port, so the
    # supervisor cannot address a SPECIFIC worker through it (the kernel
    # picks the listener). Each worker therefore also serves its full
    # router on a loopback-only ephemeral port and publishes that port
    # through the shared array — the supervisor polls sidecar /healthz.
    sidecar = None
    if health_ports is not None:
        try:
            # the sidecar stays on the threaded front regardless of
            # PIO_TPU_HTTP_FRONT: it serves /healthz to the supervisor
            # and must answer even while the main front's loop is busy
            sidecar = JsonHTTPServer(
                service.router, "127.0.0.1", 0,
                name=f"pio-tpu-health-{idx}",
            )
            sidecar.start()
            health_ports[idx] = sidecar.port
        except Exception:
            log.exception("worker %d health sidecar failed to start", idx)
            sidecar = None
    log.info("pool worker %d serving on :%d", idx, server.port)
    try:
        # POLL the event — never park in Event.wait(): a worker killed
        # while registered as a sleeper on the condition (SIGTERM/OOM,
        # i.e. exactly the crashes the supervisor exists to absorb)
        # corrupts the sleeper count, after which every set()/is_set()
        # on the SHARED event blocks forever and /undeploy can no longer
        # stop the pool. is_set() holds the internal lock only for
        # microseconds, shrinking the corruption window to ~nothing.
        # Each iteration beats the heartbeat: a wedged loop ages it out
        # and the supervisor's /healthz poll turns 503.
        from pio_tpu.faults import failpoint

        while not shutdown_evt.is_set():
            # chaos hook: `worker.serve=crash:once` kills this worker
            # mid-serve to exercise the supervisor's respawn/backoff path
            failpoint("worker.serve")
            service.heartbeat.beat()
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    if sidecar is not None:
        sidecar.stop()
    server.stop()


class ServingPool:
    """Supervisor for a fixed-size SO_REUSEPORT query-server pool."""

    def __init__(
        self,
        variant: EngineVariant,
        host: str = "0.0.0.0",
        port: int = 8000,
        n_workers: int = 2,
        instance_id: Optional[str] = None,
        feedback: bool = False,
        feedback_app_id: Optional[int] = None,
        admin_key: Optional[str] = None,
        device_worker: bool = False,
        mesh_worker: bool = False,
        slos: Optional[list] = None,
        qos: Optional[str] = None,
        http_front: Optional[str] = None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self._ctx = mp.get_context("spawn")
        self._gen = self._ctx.Value("L", 0)
        self._shutdown = self._ctx.Event()
        self._host = host
        # port 0 → reserve an ephemeral port ALL workers can share: bind a
        # SO_REUSEPORT socket here and keep it open (bound but never
        # listening, so the kernel excludes it from connection balancing)
        self._anchor: Optional[socket.socket] = None
        if port == 0:
            self._anchor = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._anchor.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
            )
            self._anchor.bind((host, 0))
            port = self._anchor.getsockname()[1]
        self.port = port
        self._spec = {
            "variant": {
                "engine_id": variant.engine_id,
                "engine_version": variant.engine_version,
                "engine_factory": variant.engine_factory,
                "variant": variant.variant,
                "path": variant.path,
            },
            "host": host,
            "port": port,
            "n_workers": n_workers,
            "instance_id": instance_id,
            "feedback": feedback,
            "feedback_app_id": feedback_app_id,
            "admin_key": admin_key,
            "device_worker": device_worker,
            "mesh_worker": mesh_worker,
            "slos": list(slos) if slos else None,
            # QoS spec string: every worker parses the same policy, and
            # because each runs identical service-init code, their QoS
            # counter cells land on the same shared-segment slots — the
            # striped token bucket depends on that alignment to enforce
            # one rps= budget POOL-WIDE (see pio_tpu/qos/limiter.py)
            "qos": qos,
            # HTTP front for every worker (threaded|evloop); None defers
            # to the worker's own PIO_TPU_HTTP_FRONT env. MUST be
            # uniform across the pool: front choice adds metric families
            # to the registry, and the shared-stripe slot layout
            # requires identical registration order in every worker
            "http_front": http_front,
        }
        self.n_workers = n_workers
        self._procs: list = []
        #: per-reason respawn counts ({"crash": n, "unhealthy": m}) —
        #: each reason spends its own budget (_MAX_RESPAWNS_BY_REASON)
        self._respawns = [
            {r: 0 for r in _MAX_RESPAWNS_BY_REASON} for _ in range(n_workers)
        ]
        #: worker i died with an exhausted budget for its kill reason and
        #: will never be respawned again
        self._retired = [False] * n_workers
        #: monotonic deadline before which worker i must NOT be respawned
        #: (0.0 = no respawn scheduled); gives crash-looping workers an
        #: exponentially growing cool-down instead of a hot spawn loop
        self._respawn_due = [0.0] * n_workers
        self._spawned_at = [0.0] * n_workers
        #: why the supervisor last killed worker i ("unhealthy" when the
        #: health sweep shot it; None → the process died on its own)
        self._kill_reason: list = [None] * n_workers
        #: sidecar health ports, published by each worker once its
        #: loopback health server is up (0 = not yet / unavailable)
        self._health_ports = self._ctx.Array("i", [0] * n_workers)
        self._health_fails = [0] * n_workers
        from pio_tpu.obs import REGISTRY

        #: 1 = healthy, 0 = failing /healthz, -1 = process dead
        self._health_gauge = REGISTRY.gauge(
            "pio_tpu_worker_health_state",
            "Supervisor view of each pool worker "
            "(1 healthy, 0 unhealthy, -1 dead)",
            ("worker",),
        )
        self._respawn_counter = REGISTRY.counter(
            "pio_tpu_worker_respawn_total",
            "Pool workers respawned by the supervisor, by cause "
            "(crash = process died on its own, unhealthy = killed "
            "after failing /healthz probes)",
            ("reason",),
        )
        # cross-worker metrics: the supervisor owns a fixed-layout
        # shared-memory segment; every worker mmaps its own stripe, so a
        # /metrics scrape on ANY worker can sum pool-wide totals
        # (pio_tpu/obs/shm.py). Creation failure degrades to per-worker
        # metrics rather than blocking serving.
        self._metrics_seg = None
        try:
            from pio_tpu.obs.shm import PoolMetricsSegment

            fd, seg_path = tempfile.mkstemp(
                prefix="pio-tpu-pool-metrics-", suffix=".shm"
            )
            os.close(fd)
            self._metrics_seg = PoolMetricsSegment.create(
                seg_path, n_workers
            )
            self._spec["metrics_path"] = seg_path
        except Exception:
            log.exception(
                "pool metrics segment creation failed; workers expose "
                "per-worker metrics only"
            )
        # cross-worker batch lane (ISSUE 7): only meaningful when ONE
        # worker owns the accelerator (device_worker) and there are
        # siblings to aggregate — a homogeneous CPU pool serves faster
        # per-process than funneled through one drainer. PIO_TPU_BATCH_LANE=0
        # force-disables.
        self._lane_seg = None
        self._lane_doorbell = None
        self._lane_resp_events = None
        if (
            (device_worker or mesh_worker) and n_workers > 1
            and knobs.knob_str("PIO_TPU_BATCH_LANE") != "0"
        ):
            try:
                from pio_tpu.server.batchlane import BatchLaneSegment

                fd, lane_path = tempfile.mkstemp(
                    prefix="pio-tpu-batch-lane-", suffix=".shm"
                )
                os.close(fd)
                self._lane_seg = BatchLaneSegment.create(
                    lane_path, n_workers
                )
                self._spec["lane_path"] = lane_path
                self._lane_doorbell = self._ctx.Event()
                self._lane_resp_events = [
                    self._ctx.Event() for _ in range(n_workers)
                ]
            except Exception:
                log.exception(
                    "batch lane segment creation failed; workers serve "
                    "locally"
                )
                self._lane_seg = None

    def _spawn(self, idx: int):
        self._health_ports[idx] = 0  # stale port from a previous life
        self._health_fails[idx] = 0
        self._spawned_at[idx] = monotonic_s()
        if getattr(self, "_metrics_seg", None) is not None:
            # stripe ownership handover (ISSUE 11): first spawn takes the
            # stripe at generation 1; every respawn bumps it so scrapers
            # can tell counter adoption from traffic
            try:
                self._metrics_seg.bump_generation(idx)
            except (OSError, ValueError, IndexError):
                log.exception("stripe generation bump failed (worker %d)",
                              idx)
        p = self._ctx.Process(
            target=_worker_main,
            args=(
                self._spec, idx, self._gen, self._shutdown,
                self._health_ports, self._lane_doorbell,
                self._lane_resp_events,
            ),
            name=f"pio-tpu-serve-{idx}",
            daemon=True,
        )
        p.start()
        return p

    def start(self) -> "ServingPool":
        self._procs = [self._spawn(i) for i in range(self.n_workers)]
        return self

    def wait_ready(self, timeout: float = 60.0) -> None:
        """Block until a worker reports READY (deploy readiness): a plain
        TCP accept is not enough — a worker accepts connections before
        its engine finished loading — so this polls ``GET /readyz`` until
        a 200 (falling back to TCP-accept only if /readyz keeps erroring
        at the HTTP layer, which cannot happen with in-tree workers)."""
        import urllib.error
        import urllib.request

        deadline = monotonic_s() + timeout
        last_err: Optional[BaseException] = None
        probe_host = (
            "127.0.0.1" if self._host in ("", "0.0.0.0", "::")
            else self._host
        )
        while monotonic_s() < deadline:
            if self._shutdown.is_set():
                raise RuntimeError("pool shut down during startup")
            try:
                with urllib.request.urlopen(
                    f"http://{probe_host}:{self.port}/readyz", timeout=2.0
                ) as r:
                    if r.status == 200:
                        return
            except urllib.error.HTTPError as e:
                last_err = e  # reachable but not ready (503) — keep polling
            except OSError as e:
                last_err = e
                if all(not p.is_alive() for p in self._procs):
                    raise RuntimeError(
                        "every pool worker exited during startup"
                    ) from e
            time.sleep(0.1)
        raise TimeoutError(
            f"no pool worker ready on :{self.port}: {last_err}"
        )

    def _poll_worker_health(self, idx: int) -> Optional[bool]:
        """One /healthz probe of worker ``idx``'s loopback sidecar.
        None = no sidecar port published yet (can't judge)."""
        port = self._health_ports[idx]
        if port <= 0:
            return None
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=1.0
            ) as r:
                return r.status == 200
        except Exception:
            # 503 raises HTTPError; a wedged worker times out — both are
            # health failures for the consecutive-failure counter
            return False

    def _health_sweep(self) -> None:
        """Poll every live worker's sidecar; kill a worker after
        ``_HEALTH_FAILS_TO_KILL`` consecutive failures so the existing
        crash-respawn path (respawn budget included) replaces it. Kill,
        not terminate: a wedged process may ignore SIGTERM."""
        for i, p in enumerate(self._procs):
            if not p.is_alive():
                self._health_gauge.set(-1, worker=str(i))
                continue
            res = self._poll_worker_health(i)
            if res is None:
                continue
            if res:
                self._health_fails[i] = 0
                self._health_gauge.set(1, worker=str(i))
                continue
            self._health_fails[i] += 1
            self._health_gauge.set(0, worker=str(i))
            log.warning(
                "worker %d failed /healthz (%d/%d consecutive)",
                i, self._health_fails[i], _HEALTH_FAILS_TO_KILL,
            )
            if self._health_fails[i] >= _HEALTH_FAILS_TO_KILL:
                log.error(
                    "worker %d unhealthy %d polls in a row; killing for "
                    "respawn", i, self._health_fails[i],
                )
                self._kill_reason[i] = "unhealthy"
                p.kill()
                p.join(timeout=2.0)

    def _account_death(self, i: int, exitcode, now: float) -> None:
        """Account one observed worker death against the kill reason's
        own respawn budget and schedule the backed-off respawn (or
        retire the worker when that reason's budget is spent)."""
        if self._retired[i]:
            return
        if (
            self._spawned_at[i] > 0.0
            and now - self._spawned_at[i] >= _RESPAWN_RESET_AFTER_S
        ):
            # long-lived worker: this death is not a crash loop
            for r in self._respawns[i]:
                self._respawns[i][r] = 0
        reason = self._kill_reason[i] or "crash"
        self._kill_reason[i] = None
        budget = _MAX_RESPAWNS_BY_REASON.get(reason, _MAX_RESPAWNS)
        if self._respawns[i].get(reason, 0) >= budget:
            log.error(
                "worker %d died %d times (reason %s); not respawning",
                i, self._respawns[i][reason], reason,
            )
            self._retired[i] = True
            if getattr(self, "_metrics_seg", None) is not None:
                # freeze the stripe: negative generation marks "retired,
                # totals retained" so pool/fleet scrapes keep the sums
                # but know they will never move again
                try:
                    self._metrics_seg.retire_stripe(i)
                except (OSError, ValueError, IndexError):
                    log.exception(
                        "stripe retirement failed (worker %d)", i
                    )
            return
        self._respawns[i][reason] = self._respawns[i].get(reason, 0) + 1
        self._respawn_counter.inc(reason=reason)
        # backoff grows with THIS reason's streak: a worker the health
        # sweep shot once does not inherit the cool-down its earlier
        # crashes earned
        delay = min(
            _RESPAWN_BACKOFF_CAP_S,
            _RESPAWN_BACKOFF_BASE_S
            * 2 ** (self._respawns[i][reason] - 1),
        )
        self._respawn_due[i] = now + delay
        log.warning(
            "worker %d exited (code %s, reason %s); respawning in "
            "%.1fs (%d/%d)",
            i, exitcode, reason, delay, self._respawns[i][reason], budget,
        )

    def wait(self, poll_s: float = 0.5,
             health_poll_s: float = 2.0) -> None:
        """Supervise until /undeploy (or stop()): respawn crashed workers
        within budget, kill-and-respawn workers that fail /healthz
        ``_HEALTH_FAILS_TO_KILL`` polls in a row, then reap everything
        once the event fires."""
        next_health = monotonic_s() + health_poll_s
        while not self._shutdown.is_set():
            if monotonic_s() >= next_health:
                next_health = monotonic_s() + health_poll_s
                self._health_sweep()
            now = monotonic_s()
            for i, p in enumerate(self._procs):
                if p.is_alive() or self._shutdown.is_set():
                    continue
                if self._respawn_due[i] > 0.0:
                    # phase 2: a respawn is scheduled — spawn once the
                    # backoff cool-down has elapsed
                    if now >= self._respawn_due[i]:
                        self._respawn_due[i] = 0.0
                        self._procs[i] = self._spawn(i)
                    continue
                # phase 1: first observation of this death — account for
                # it and schedule the (possibly delayed) respawn
                self._account_death(i, p.exitcode, now)
            if all(
                not p.is_alive() for p in self._procs
            ) and all(self._retired) and not any(
                d > 0.0 for d in self._respawn_due
            ):
                log.error("all workers dead and out of respawn budget")
                break
            # plain sleep, not Event.wait(): nobody ever registers as a
            # sleeper on the shared event, so a killed process can never
            # corrupt it (see the matching note in _worker_main)
            time.sleep(poll_s)
        self.stop()

    def stop(self, join_timeout: float = 5.0) -> None:
        self._shutdown.set()
        for p in self._procs:
            p.join(timeout=join_timeout)
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
        if self._anchor is not None:
            self._anchor.close()
            self._anchor = None
        if self._metrics_seg is not None:
            try:
                self._metrics_seg.close()
                self._metrics_seg.unlink()
            except OSError:
                pass
            self._metrics_seg = None
        if self._lane_seg is not None:
            try:
                self._lane_seg.close()
                self._lane_seg.unlink()
            except OSError:
                pass
            self._lane_seg = None
