"""Shape-bucket execution cache for the serving hot path.

JAX specializes a compiled executable per input SHAPE: every fresh batch
size that reaches ``algo.batch_predict`` pays an XLA trace+compile
(seconds-scale) before the first byte of useful work. Under a
micro-batching server the batch size is whatever concurrency happened to
produce — a stream of fresh shapes — so batching loses exactly where it
should win (the round-4 probe measured batched p50 10.7 ms vs 0.4 ms
per-query, all of it retrace jitter).

The fix is the oldest trick in serving systems: quantize. Batches are
padded up to a small, fixed set of bucket sizes (default 1/2/4/8/16/32,
env-tunable via ``PIO_TPU_BATCH_BUCKETS``), every bucket's executable is
compiled ONCE by a warmup sweep at deploy/hot-swap, and the hot path
only ever dispatches bucket-shaped batches — a pure cache hit in jit's
shape-keyed executable cache, never a retrace. Oversized batches chunk
into max-bucket pieces.

The cache itself holds no executables (those live in the per-scorer /
per-model jit caches, keyed by shape); it owns the POLICY and the
ACCOUNTING: which bucket a batch lands in, which buckets are warmed for
the currently deployed model generation, and the retrace/dispatch/
occupancy counters that make "steady-state dispatches never retrace"
an assertable property (smoke asserts it; the bench records it).

Hot-swap semantics: a /reload warms the NEW model's buckets before the
swap is visible (the sweep runs on the incoming pairs while the old
model keeps serving), then :meth:`install` atomically replaces the
warmed set — the old generation's entries are evicted with it.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, List, Optional, Sequence, Tuple

from pio_tpu.analysis.runtime import make_lock

log = logging.getLogger("pio_tpu.bucketcache")

#: default bucket ladder — powers of two up to the micro-batcher's
#: practical occupancy; matches ops/topn.py's internal pow2 bucketing so
#: serving-layer buckets and scorer-layer buckets coincide
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def buckets_from_env(env: str = "PIO_TPU_BATCH_BUCKETS") -> Tuple[int, ...]:
    """Bucket ladder from the environment: a comma-separated list of
    positive ints (``"1,4,16"``). Malformed values fall back to the
    default with a warning — a typo'd ladder must degrade, not take the
    server down at boot."""
    raw = os.environ.get(env, "")
    if not raw.strip():
        return DEFAULT_BUCKETS
    try:
        vals = sorted({int(tok) for tok in raw.split(",") if tok.strip()})
        if not vals or any(v < 1 for v in vals):
            raise ValueError(raw)
        return tuple(vals)
    except ValueError:
        log.warning(
            "malformed %s=%r; using default buckets %s",
            env, raw, DEFAULT_BUCKETS,
        )
        return DEFAULT_BUCKETS


class BucketExecutionCache:
    """Bucket policy + warmed-generation bookkeeping for one engine.

    Thread-safe: the warmed set is read on every dispatch (hot path) and
    replaced wholesale on hot-swap; a lock guards the mutations, reads
    go through an immutable frozenset snapshot.
    """

    def __init__(self, buckets: Optional[Sequence[int]] = None):
        self.buckets: Tuple[int, ...] = (
            tuple(sorted(set(buckets))) if buckets else buckets_from_env()
        )
        if any(b < 1 for b in self.buckets):
            raise ValueError(f"bucket sizes must be >= 1: {self.buckets}")
        self.max_bucket = self.buckets[-1]
        self._lock = make_lock("query.bucket_cache")
        #: buckets whose executable the CURRENT model generation compiled
        self._warmed: frozenset = frozenset()
        self.generation = 0
        self.evictions = 0
        self.retraces = 0

    # -- policy ------------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (chunk-sized inputs; n > max never
        reaches here — see :meth:`chunks`)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_bucket

    def chunks(self, n: int) -> List[int]:
        """Split a batch of ``n`` into max-bucket-sized chunk lengths."""
        out = []
        while n > self.max_bucket:
            out.append(self.max_bucket)
            n -= self.max_bucket
        if n:
            out.append(n)
        return out

    def pad(self, queries: list) -> Tuple[list, int]:
        """Pad a chunk (len <= max bucket) up to its bucket by
        replicating the last query — the padding rows ride the same
        compiled program and their results are sliced off. Returns
        ``(padded, bucket)``."""
        b = self.bucket_for(len(queries))
        if len(queries) == b:
            return queries, b
        return queries + [queries[-1]] * (b - len(queries)), b

    # -- warm/evict lifecycle ---------------------------------------------
    def note_dispatch(self, bucket: int) -> bool:
        """Record a hot-path dispatch into ``bucket``. Returns True when
        the bucket was NOT warmed for the current generation — a retrace:
        the dispatch is paying a compile the warmup sweep should have
        absorbed. The bucket is marked warmed so each shape retraces at
        most once per generation."""
        if bucket in self._warmed:
            return False
        with self._lock:
            if bucket in self._warmed:
                return False
            self._warmed = self._warmed | {bucket}
            self.retraces += 1
        return True

    def install(self, warmed: Sequence[int]) -> int:
        """Atomically swap in a new generation's warmed set (hot-swap
        eviction: whatever the old generation had compiled is dead —
        the new model's shapes/weights own the jit caches now).
        Returns the new generation number so callers that co-version
        other per-generation state (device-resident scorers) can stamp
        it."""
        with self._lock:
            if self._warmed:
                self.evictions += len(self._warmed)
            self._warmed = frozenset(warmed)
            self.generation += 1
            return self.generation

    @property
    def warmed(self) -> frozenset:
        return self._warmed

    def to_dict(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "warmed": sorted(self._warmed),
            "generation": self.generation,
            "retraces": self.retraces,
            "evictions": self.evictions,
        }


# pio: hotpath
def dispatch_bucketed(
    cache: BucketExecutionCache,
    queries: list,
    run_batch: Callable[[list], list],
    on_dispatch: Optional[Callable[[int, int, bool], None]] = None,
) -> Tuple[list, bool]:
    """Serve ``queries`` through bucket-shaped ``run_batch`` calls.

    Chunks to the max bucket, pads each chunk to its bucket, slices the
    padding rows back off, and reports ``(results, fresh)`` where
    ``fresh`` is True when ANY chunk hit a cold bucket (the caller —
    the micro-batcher's probe — discards such samples as compile
    transients). ``on_dispatch(n, bucket, fresh)`` fires per chunk for
    metric accounting.
    """
    results: list = []
    fresh_any = False
    pos = 0
    for n in cache.chunks(len(queries)):
        chunk = queries[pos:pos + n]
        pos += n
        padded, bucket = cache.pad(chunk)
        fresh = cache.note_dispatch(bucket)
        fresh_any = fresh_any or fresh
        got = run_batch(padded)
        results.extend(got[:n])
        if on_dispatch is not None:
            on_dispatch(n, bucket, fresh)
    return results, fresh_any
