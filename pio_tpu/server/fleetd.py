"""Fleet telemetry daemon — the standalone home of the aggregator.

``pio fleet --targets host:port,...`` builds a :class:`FleetService`:
one :class:`~pio_tpu.obs.fleet.FleetAggregator` scraping the member
list on a jittered interval, served over the shared HTTP plumbing.

Routes:

- ``GET /fleet.json`` — the federated cluster status payload (the
  ROADMAP-item-2 router contract; schema in docs/observability.md);
- ``GET /metrics``    — the aggregator's own ``pio_tpu_fleet_*``
  families plus the union of every member's metrics, each sample
  labeled ``pio_tpu_member="host:port"``;
- ``GET /healthz`` / ``GET /readyz`` — ready once one full scrape pass
  has completed (the router must not steer by an empty snapshot);
- ``GET /`` — tiny JSON index.

This module also hosts :class:`FollowerStatusService`: a partlog
:class:`~pio_tpu.storage.partlog.replication.FollowerServer` speaks a
raw socket protocol and has no HTTP surface of its own, so the smoke
fleet stage (and any real read-replica deployment) wraps it in this
member-shaped sidecar — ``/metrics`` with per-partition mirrored-byte
positions, ``/readyz``, and a ``role: follower`` ``/storage.json``.

And :class:`TrainStatusService` (ISSUE 16): ``pio train`` is a
daemonless driver process, so its live progress sidecar rides here —
``/train.json`` (the trainwatch recorder's progress payload),
``/device.json`` (the active devicewatch's HBM + compile table,
ISSUE 17), ``/metrics`` (the process-global registry: the run's
``pio_tpu_train_*`` and ``pio_tpu_device_*``/``pio_tpu_xla_*``
families), ``/logs.json`` (the slog ring, filterable by the run's
trace id) and the health pair. A FleetAggregator scraping it shows a
``role: trainer`` member for the run's duration.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Tuple

from pio_tpu.obs import HealthMonitor, MetricsRegistry
from pio_tpu.obs import slog
from pio_tpu.obs.fleet import FleetAggregator, parse_targets
from pio_tpu.server.http import (
    JsonHTTPServer, Request, Router, metrics_response,
)


class FleetService:
    """Aggregator + routes; ``create_fleet_server`` wires it to a port."""

    def __init__(
        self,
        targets: List[Tuple[str, str]],
        interval_s: Optional[float] = None,
        fetch=None,
    ):
        if not targets:
            raise ValueError(
                "fleet needs at least one target "
                "(--targets host:port,... or PIO_TPU_FLEET_TARGETS)"
            )
        self.obs = MetricsRegistry()
        slog.install()
        self.obs.add_collector(slog.exposition_lines)
        self.agg = FleetAggregator(
            targets, registry=self.obs, interval_s=interval_s, fetch=fetch,
        )
        self.health = HealthMonitor()
        self.health.add_readiness("first_scrape", self._check_first_scrape)
        self.router = Router()
        self.router.add("GET", "/", self.index)
        self.router.add("GET", "/fleet\\.json", self.fleet_json)
        self.router.add("GET", "/metrics", self.get_metrics)
        self.router.add("GET", "/healthz", self.healthz)
        self.router.add("GET", "/readyz", self.readyz)

    def _check_first_scrape(self):
        if self.agg.passes < 1:
            return False, "no scrape pass completed yet"
        return True, f"{self.agg.passes} scrape passes"

    def index(self, req: Request) -> Tuple[int, Any]:
        return 200, {
            "service": "pio-tpu-fleetd",
            "members": [m.name for m in self.agg.members()],
            "endpoints": ["/fleet.json", "/metrics", "/healthz", "/readyz"],
        }

    def fleet_json(self, req: Request) -> Tuple[int, Any]:
        return 200, self.agg.fleet_payload()

    def get_metrics(self, req: Request) -> Tuple[int, Any]:
        return 200, metrics_response(self.obs.render())

    def healthz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.readiness()
        return (200 if ok else 503), report


def create_fleet_server(
    targets: str,
    host: str = "0.0.0.0",
    port: int = 7000,
    interval_s: Optional[float] = None,
) -> JsonHTTPServer:
    """Build (unstarted) fleet daemon; the caller starts the HTTP server
    and then :meth:`FleetAggregator.start` via ``server.service.agg``."""
    service = FleetService(parse_targets(targets), interval_s=interval_s)
    server = JsonHTTPServer(
        service.router, host, port, name="pio-tpu-fleetd"
    )
    server.service = service
    return server


# ---------------------------------------------------------------------------
# follower observability sidecar
# ---------------------------------------------------------------------------

class FollowerStatusService:
    """Member-shaped HTTP surface for one partlog follower."""

    def __init__(self, follower):
        #: duck-typed FollowerServer: .root, .host, .port, .positions(n)
        self.follower = follower
        self.obs = MetricsRegistry()
        self._position = self.obs.gauge(
            "pio_tpu_repl_follower_position_bytes",
            "Verified mirrored bytes per partition on this follower",
            ("partition",),
        )
        self.health = HealthMonitor()
        self.health.add_readiness("mirror_root", self._check_root)
        self.router = Router()
        self.router.add("GET", "/storage\\.json", self.storage_json)
        self.router.add("GET", "/metrics", self.get_metrics)
        self.router.add("GET", "/healthz", self.healthz)
        self.router.add("GET", "/readyz", self.readyz)

    def _partitions(self) -> int:
        """Partition count from the MANIFEST the leader handshake wrote
        (0 until the first leader connects)."""
        path = os.path.join(self.follower.root, "MANIFEST.json")
        try:
            with open(path) as f:
                return int(json.load(f).get("partitions", 0))
        except (OSError, ValueError):
            return 0

    def _positions(self) -> dict:
        n = self._partitions()
        return self.follower.positions(n) if n else {}

    def _check_root(self):
        if not os.path.isdir(self.follower.root):
            return False, f"mirror root missing: {self.follower.root}"
        return True, self.follower.root

    def storage_json(self, req: Request) -> Tuple[int, Any]:
        pos = self._positions()
        return 200, {
            "backend": "partlog",
            "role": "follower",
            "root": self.follower.root,
            "partitions": self._partitions(),
            "replicationPort": self.follower.port,
            "positions": {str(k): v for k, v in pos.items()},
        }

    def get_metrics(self, req: Request) -> Tuple[int, Any]:
        for k, v in self._positions().items():
            self._position.set(float(v), partition=str(k))
        return 200, metrics_response(self.obs.render())

    def healthz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.readiness()
        return (200 if ok else 503), report


def create_follower_status_server(
    follower, host: str = "127.0.0.1", port: int = 0,
) -> JsonHTTPServer:
    """Wrap a running FollowerServer in its observability sidecar."""
    service = FollowerStatusService(follower)
    server = JsonHTTPServer(
        service.router, host, port, name="pio-tpu-follower-status"
    )
    server.service = service
    return server


# ---------------------------------------------------------------------------
# trainer observability sidecar (ISSUE 16)
# ---------------------------------------------------------------------------

class TrainStatusService:
    """Member-shaped HTTP surface for one in-flight training run.

    Reads the PROCESS-GLOBAL state (the active trainwatch recorder, the
    global metrics registry, the slog ring) rather than holding its own:
    training runs in the driver process and the sidecar thread must see
    whatever run is live, including one that starts after the server.
    """

    def __init__(self):
        from pio_tpu.obs import REGISTRY

        self._registry = REGISTRY
        self.health = HealthMonitor()
        self.health.add_readiness("training_run", self._check_run)
        self.router = Router()
        self.router.add("GET", "/train\\.json", self.train_json)
        self.router.add("GET", "/device\\.json", self.device_json)
        self.router.add("GET", "/logs\\.json", self.logs_json)
        self.router.add("GET", "/metrics", self.get_metrics)
        self.router.add("GET", "/healthz", self.healthz)
        self.router.add("GET", "/readyz", self.readyz)

    def _check_run(self):
        from pio_tpu.obs import trainwatch

        rec = trainwatch.active_recorder()
        if rec is None:
            return False, "no active training run"
        return True, f"run {rec.run_id}"

    def train_json(self, req: Request) -> Tuple[int, Any]:
        from pio_tpu.obs import trainwatch

        rec = trainwatch.active_recorder()
        if rec is None:
            return 503, {"error": "no active training run"}
        return 200, rec.payload()

    def device_json(self, req: Request) -> Tuple[int, Any]:
        """The run's device telemetry (ISSUE 17): the driver thread
        activates a DeviceWatch for the run; like /train.json, the
        sidecar reads whatever watch is live in the process."""
        from pio_tpu.obs import devicewatch

        watch = devicewatch.active_watch()
        if watch is None:
            return 503, {"error": "no active device watch"}
        return 200, watch.payload()

    def logs_json(self, req: Request) -> Tuple[int, Any]:
        from pio_tpu.server.http import int_param

        n = int_param(req.params, "n", 100, lo=0, hi=slog.ring().cap)
        return 200, slog.logs_payload(
            n=n,
            level=req.params.get("level"),
            trace_id=req.params.get("trace_id"),
            logger=req.params.get("logger"),
        )

    def get_metrics(self, req: Request) -> Tuple[int, Any]:
        return 200, metrics_response(self._registry.render())

    def healthz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.readiness()
        return (200 if ok else 503), report


def create_train_status_server(
    host: str = "127.0.0.1", port: int = 0,
) -> JsonHTTPServer:
    """Build (unstarted) trainer sidecar; ``pio train --status-port``
    starts it for the run's duration (default loopback + ephemeral
    port — the run prints the bound port once the server starts)."""
    service = TrainStatusService()
    server = JsonHTTPServer(
        service.router, host, port, name="pio-tpu-train-status"
    )
    server.service = service
    return server
