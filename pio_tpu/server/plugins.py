"""Server plugin framework — ingest- and query-path hooks.

Rebuild of the reference's ServiceLoader-discovered plugins
(``data/.../api/EventServerPlugin.scala`` and
``core/.../workflow/EngineServerPlugin.scala`` + their PluginContext/Actors —
UNVERIFIED paths; SURVEY.md §2.1/§2.2): *input blockers* can reject an event
before it is persisted, *input sniffers* observe accepted events, *output
blockers* veto/transform query responses, *output sniffers* observe them.

Java ServiceLoader discovery becomes Python module discovery: set
``PIO_TPU_PLUGINS=my_mod,other_mod`` and each module is imported at server
start; modules call :func:`register_plugin` at import time. Both servers
expose ``GET /plugins.json`` listing what's installed.
"""

from __future__ import annotations

import abc
import importlib
import logging
import os
from typing import Any, Dict, List, Optional

log = logging.getLogger("pio_tpu.plugins")

# plugin_type values (reference constants on both plugin traits)
INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"
OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EventServerPlugin(abc.ABC):
    """Ingest-path hook (reference ``EventServerPlugin``).

    ``plugin_type`` is :data:`INPUT_BLOCKER` (``process`` may raise
    ``ValueError`` to reject the event with a 400) or :data:`INPUT_SNIFFER`
    (exceptions are logged and swallowed).
    """

    plugin_name: str = "unnamed"
    plugin_description: str = ""
    plugin_type: str = INPUT_SNIFFER

    @abc.abstractmethod
    def process(
        self, event: Dict[str, Any], app_id: int, channel_id: Optional[int]
    ) -> None: ...


class EngineServerPlugin(abc.ABC):
    """Query-path hook (reference ``EngineServerPlugin``).

    ``plugin_type`` is :data:`OUTPUT_BLOCKER` (``process`` may raise
    ``ValueError`` to fail the query) or :data:`OUTPUT_SNIFFER`.
    """

    plugin_name: str = "unnamed"
    plugin_description: str = ""
    plugin_type: str = OUTPUT_SNIFFER

    @abc.abstractmethod
    def process(self, query: Any, prediction: Any) -> None: ...


_event_plugins: List[EventServerPlugin] = []
_engine_plugins: List[EngineServerPlugin] = []


def register_plugin(plugin) -> None:
    """Install a plugin instance into the matching server hook list.

    Rejects unknown ``plugin_type`` values — a typo'd blocker silently
    installed as a sniffer would stop blocking.
    """
    from pio_tpu.server import event_server, query_server

    if isinstance(plugin, EventServerPlugin):
        if plugin.plugin_type not in (INPUT_BLOCKER, INPUT_SNIFFER):
            raise ValueError(
                f"EventServerPlugin.plugin_type must be {INPUT_BLOCKER!r} "
                f"or {INPUT_SNIFFER!r}, got {plugin.plugin_type!r}"
            )
        _event_plugins.append(plugin)
        hook = lambda app_id, channel_id, d: plugin.process(d, app_id, channel_id)
        if plugin.plugin_type == INPUT_BLOCKER:
            event_server.INPUT_BLOCKERS.append(hook)
        else:
            event_server.INPUT_SNIFFERS.append(hook)
    elif isinstance(plugin, EngineServerPlugin):
        if plugin.plugin_type not in (OUTPUT_BLOCKER, OUTPUT_SNIFFER):
            raise ValueError(
                f"EngineServerPlugin.plugin_type must be {OUTPUT_BLOCKER!r} "
                f"or {OUTPUT_SNIFFER!r}, got {plugin.plugin_type!r}"
            )
        _engine_plugins.append(plugin)
        hook = lambda body, out: plugin.process(body, out)
        if plugin.plugin_type == OUTPUT_BLOCKER:
            query_server.QUERY_BLOCKERS.append(hook)
        else:
            query_server.QUERY_SNIFFERS.append(hook)
    else:
        raise TypeError(
            "plugin must be an EventServerPlugin or EngineServerPlugin"
        )


def clear_plugins() -> None:
    """Uninstall everything (tests)."""
    from pio_tpu.server import event_server, query_server

    _event_plugins.clear()
    _engine_plugins.clear()
    event_server.INPUT_BLOCKERS.clear()
    event_server.INPUT_SNIFFERS.clear()
    query_server.QUERY_BLOCKERS.clear()
    query_server.QUERY_SNIFFERS.clear()


def installed_plugins() -> Dict[str, List[dict]]:
    """Listing for ``GET /plugins.json`` (reference plugins route)."""

    def entry(p):
        return {
            "name": p.plugin_name,
            "description": p.plugin_description,
            "type": p.plugin_type,
        }

    return {
        "eventServerPlugins": [entry(p) for p in _event_plugins],
        "engineServerPlugins": [entry(p) for p in _engine_plugins],
    }


def load_plugins_from_env(env_var: str = "PIO_TPU_PLUGINS") -> List[str]:
    """Import each module named in ``$PIO_TPU_PLUGINS`` (comma-separated).

    Modules self-register via :func:`register_plugin` at import time — the
    Python analog of META-INF/services discovery. Returns the modules loaded.
    """
    import sys

    loaded = []
    for name in filter(None, os.environ.get(env_var, "").split(",")):
        name = name.strip()
        try:
            if name in sys.modules:
                # a cached import would skip the module's register_plugin
                # calls (e.g. after clear_plugins() on redeploy) — re-run it
                importlib.reload(sys.modules[name])
            else:
                importlib.import_module(name)
            loaded.append(name)
        except Exception:
            log.exception("failed to load plugin module %s", name)
    return loaded
