"""Blob daemon — the remote Models endpoint filling the HDFS/S3 slot.

The reference's HDFS/S3 model stores (``storage/hdfs/.../HDFSModels.scala``,
``storage/s3/.../S3Models.scala`` — UNVERIFIED paths; SURVEY.md §2.3) put
model artifacts behind a NETWORK service. This daemon is that service for
the TPU rebuild: a flat key → bytes store served over HTTP from a local
root, consumed by the ``http://`` scheme registered in
``pio_tpu.storage.blobstore`` — so a training host can persist models to a
storage host and a serving host can load them, with nothing shared but a
socket. Content addressing, digest verification, dedupe, and ref-count GC
all live in the client (:class:`~pio_tpu.storage.blobstore.BlobModels`);
the daemon stays a dumb byte store, exactly like S3/HDFS under the
reference's stores.

Routes (keys are percent-encoded path remainders; bodies are raw bytes):

    GET    /blobs/<key>      blob bytes | 404
    HEAD   /blobs/<key>      existence probe
    PUT    /blobs/<key>      store body bytes (201)
    DELETE /blobs/<key>      200 | 404
    GET    /keys?prefix=p    JSON list of keys under a prefix
    GET    /                 health/info

Auth: optional shared key — ``create_blob_server(..., access_key=...)``
requires ``Authorization: Bearer <key>`` (or ``?accessKey=``) on every
route. TLS via the shared ``PIO_TPU_SSL_*`` env (server/http.py).

Start one with the CLI: ``python -m pio_tpu blobserver --root /var/blobs``.
"""

from __future__ import annotations

from typing import Optional
from urllib.parse import unquote

from pio_tpu.server.http import (
    FileResponse, HTTPError, JsonHTTPServer, Request, Router, keys_equal,
)
from pio_tpu.storage.blobstore import FileBlobBackend


class BlobServerService:
    """Route handlers over a :class:`FileBlobBackend` root."""

    def __init__(self, root: str, access_key: Optional[str] = None):
        self.backend = FileBlobBackend(root)
        self.access_key = access_key
        self.router = Router()
        r = self.router
        r.add("GET", "/", self.info)
        r.add("GET", "/blobs/(.+)", self.get_blob)
        r.add("HEAD", "/blobs/(.+)", self.head_blob)
        r.add("PUT", "/blobs/(.+)", self.put_blob)
        r.add("DELETE", "/blobs/(.+)", self.delete_blob)
        r.add("GET", "/keys", self.list_keys)

    def _auth(self, req: Request) -> None:
        if self.access_key is not None and not keys_equal(
            req.bearer_key(), self.access_key
        ):
            raise HTTPError(401, "invalid accessKey")

    @staticmethod
    def _key(req: Request) -> str:
        key = unquote(req.path_args[0])
        if not key or key.startswith("/") or ".." in key.split("/"):
            raise HTTPError(400, f"invalid blob key {key!r}")
        return key

    def info(self, req: Request):
        self._auth(req)
        return 200, {"status": "alive", "service": "pio-tpu-blobserver"}

    def get_blob(self, req: Request):
        self._auth(req)
        path = self.backend.local_path(self._key(req))
        if path is None:
            raise HTTPError(404, "no such blob")
        # streamed in constant memory — concurrent GETs of a multi-GB
        # model must not each buffer the whole artifact
        return 200, FileResponse(path)

    def head_blob(self, req: Request):
        self._auth(req)
        if not self.backend.exists(self._key(req)):
            raise HTTPError(404, "no such blob")
        return 200, None

    def put_blob(self, req: Request):
        self._auth(req)
        if req.body_file is not None:
            # large uploads arrive spooled — stream to disk, never buffer
            n = self.backend.put_file(self._key(req), req.body_file)
        else:
            n = len(req.raw_body)
            self.backend.put(self._key(req), req.raw_body)
        return 201, {"stored": n}

    def delete_blob(self, req: Request):
        self._auth(req)
        if not self.backend.delete(self._key(req)):
            raise HTTPError(404, "no such blob")
        return 200, {"deleted": True}

    def list_keys(self, req: Request):
        self._auth(req)
        return 200, {"keys": self.backend.list(req.params.get("prefix", ""))}


def create_blob_server(
    root: str,
    host: str = "0.0.0.0",
    port: int = 7088,
    access_key: Optional[str] = None,
) -> JsonHTTPServer:
    """Build an (unstarted) blob daemon serving ``root`` over HTTP."""
    service = BlobServerService(root, access_key=access_key)
    return JsonHTTPServer(
        service.router, host, port, name="pio-tpu-blobserver",
        # reject bad keys BEFORE the body is spooled off the socket —
        # an unauthenticated PUT must not burn disk up to the body limit
        pre_body=service._auth,
        # the blob daemon is the ONE server allowed multi-GB
        # octet-stream bodies (pre-body-authenticated); every other
        # server keeps the tight structured-body cap for raw uploads too
        large_uploads=True,
    )
