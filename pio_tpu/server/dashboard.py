"""Dashboard server — evaluation results UI.

Rebuild of the reference's ``tools/.../tools/dashboard/`` (Dashboard.scala,
DashboardService + Twirl templates, CORS support — UNVERIFIED paths;
SURVEY.md §2.4): a web UI listing completed ``EvaluationInstances`` newest
first with metric scores and the parameters that produced them.

Routes:

- ``GET /``                      — HTML table of completed evaluations;
- ``GET /instances.json``        — same data as JSON;
- ``GET /instances/<id>.json``   — one instance incl. full evaluator results;
- ``GET /instances/<id>.html``   — the instance's stored HTML report.

All responses carry ``Access-Control-Allow-Origin: *`` (reference
``CorsSupport``).
"""

from __future__ import annotations

import html as _html
import json
from typing import Any, Tuple

from pio_tpu.server.http import JsonHTTPServer, RawResponse, Request, Router
from pio_tpu.storage import RunStatus, Storage

_CORS = {"Access-Control-Allow-Origin": "*"}


def _html_response(page: str) -> RawResponse:
    return RawResponse(page, headers=dict(_CORS))


def _instance_summary(inst) -> dict:
    return {
        "id": inst.id,
        "status": inst.status,
        "startTime": inst.start_time.isoformat(),
        "endTime": inst.end_time.isoformat(),
        "evaluationClass": inst.evaluation_class,
        "engineParamsGeneratorClass": inst.engine_params_generator_class,
        "batch": inst.batch,
        "evaluatorResults": inst.evaluator_results,
    }


class DashboardService:
    """≙ reference ``DashboardService`` routes."""

    def __init__(self):
        self.router = Router()
        self.router.add("GET", "/", self.index)
        self.router.add("GET", "/instances\\.json", self.list_json)
        self.router.add("GET", "/instances/([^/]+)\\.json", self.get_json)
        self.router.add("GET", "/instances/([^/]+)\\.html", self.get_html)

    def _completed(self):
        return Storage.get_meta_data_evaluation_instances().get_completed()

    def index(self, req: Request) -> Tuple[int, Any]:
        rows = []
        for i in self._completed():
            rows.append(
                "<tr>"
                f"<td><a href='/instances/{_html.escape(i.id)}.html'>"
                f"{_html.escape(i.id)}</a></td>"
                f"<td>{_html.escape(i.evaluation_class)}</td>"
                f"<td>{_html.escape(i.start_time.isoformat())}</td>"
                f"<td>{_html.escape(i.end_time.isoformat())}</td>"
                f"<td>{_html.escape(i.evaluator_results)}</td>"
                "</tr>"
            )
        page = (
            "<!doctype html><html><head><title>pio-tpu dashboard</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:.4em .8em;text-align:left}</style></head><body>"
            "<h1>Evaluation Dashboard</h1>"
            "<table><tr><th>Instance</th><th>Evaluation</th><th>Start</th>"
            "<th>End</th><th>Result</th></tr>"
            + "".join(rows)
            + "</table></body></html>"
        )
        return 200, _html_response(page)

    def list_json(self, req: Request) -> Tuple[int, Any]:
        return 200, [_instance_summary(i) for i in self._completed()]

    def _find(self, instance_id: str):
        return Storage.get_meta_data_evaluation_instances().get(instance_id)

    def get_json(self, req: Request) -> Tuple[int, Any]:
        inst = self._find(req.path_args[0])
        if inst is None:
            return 404, {"message": "evaluation instance not found"}
        out = _instance_summary(inst)
        try:
            out["results"] = json.loads(inst.evaluator_results_json or "null")
        except json.JSONDecodeError:
            out["results"] = None
        return 200, out

    def get_html(self, req: Request) -> Tuple[int, Any]:
        inst = self._find(req.path_args[0])
        if inst is None:
            return 404, {"message": "evaluation instance not found"}
        body = inst.evaluator_results_html or (
            "<html><body><pre>"
            + _html.escape(inst.evaluator_results_json or "(no results)")
            + "</pre></body></html>"
        )
        return 200, _html_response(body)


def create_dashboard(
    host: str = "0.0.0.0", port: int = 9000
) -> JsonHTTPServer:
    """Build (unstarted) dashboard — reference ``Dashboard.main``."""
    service = DashboardService()
    return JsonHTTPServer(service.router, host, port, name="pio-tpu-dashboard")
