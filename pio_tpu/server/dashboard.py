"""Dashboard server — evaluation results UI.

Rebuild of the reference's ``tools/.../tools/dashboard/`` (Dashboard.scala,
DashboardService + Twirl templates, CORS support — UNVERIFIED paths;
SURVEY.md §2.4): a web UI listing completed ``EvaluationInstances`` newest
first with metric scores and the parameters that produced them.

Routes:

- ``GET /``                      — HTML table of completed evaluations;
- ``GET /instances.json``        — same data as JSON;
- ``GET /instances/<id>.json``   — one instance incl. full evaluator results;
- ``GET /instances/<id>.html``   — the instance's stored HTML report;
- ``GET /serving.html``          — live serving view: pool-wide request
  totals + per-stage latency table scraped from a query server's
  ``/metrics`` (ISSUE 1 observability surface);
- ``GET /fleet.html``            — fleet panel (ISSUE 11): member
  liveness, replication lag and SLO burn rollup from an embedded
  :class:`~pio_tpu.obs.fleet.FleetAggregator` (enabled by passing
  ``fleet_targets`` / setting ``PIO_TPU_FLEET_TARGETS``);
- ``GET /fleet.json``            — the same aggregator's router contract;
- ``GET /training.html``         — live training progress (ISSUE 16):
  one scrape of a ``pio train`` status sidecar's ``/train.json``
  (``--train-url`` / ``PIO_TPU_TRAIN_STATUS_URL``, or ``?url=``);
- ``GET /metrics``               — the dashboard's own scrape endpoint
  (carries the federated member metrics when the fleet panel is on).

All responses carry ``Access-Control-Allow-Origin: *`` (reference
``CorsSupport``).
"""

from __future__ import annotations

import html as _html
import json
from typing import Any, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.obs import HealthMonitor, MetricsRegistry
from pio_tpu.obs import slog
from pio_tpu.obs.promparse import ParsedMetrics, parse_prometheus_text
from pio_tpu.server.http import (
    HTTPError, JsonHTTPServer, RawResponse, Request, Router, int_param,
    metrics_response,
)
from pio_tpu.storage import RunStatus, Storage

_CORS = {"Access-Control-Allow-Origin": "*"}


def _html_response(page: str) -> RawResponse:
    return RawResponse(page, headers=dict(_CORS))


def _instance_summary(inst) -> dict:
    return {
        "id": inst.id,
        "status": inst.status,
        "startTime": inst.start_time.isoformat(),
        "endTime": inst.end_time.isoformat(),
        "evaluationClass": inst.evaluation_class,
        "engineParamsGeneratorClass": inst.engine_params_generator_class,
        "batch": inst.batch,
        "evaluatorResults": inst.evaluator_results,
    }


class DashboardService:
    """≙ reference ``DashboardService`` routes (+ the serving view)."""

    def __init__(self, query_url: str = "http://127.0.0.1:8000",
                 fleet_targets: Optional[str] = None,
                 train_url: Optional[str] = None):
        #: base URL of the query server (or any pool worker — in pool
        #: mode every worker's /metrics reports pool-wide totals) whose
        #: serving metrics /serving.html renders
        self.query_url = query_url.rstrip("/")
        import os as _os0

        #: base URL of a `pio train` status sidecar whose /train.json
        #: the /training.html view follows
        self.train_url = (
            train_url or knobs.knob_str("PIO_TPU_TRAIN_STATUS_URL")
        ).rstrip("/")
        self.obs = MetricsRegistry()
        self._pageviews = self.obs.counter(
            "pio_tpu_dashboard_pageviews_total",
            "Dashboard page renders",
            ("page",),
        )
        slog.install()
        self.obs.add_collector(slog.exposition_lines)
        self.health = HealthMonitor()
        self.health.add_readiness("storage", self._check_storage_ready)
        # embedded fleet aggregator (ISSUE 11): the lightweight
        # alternative to a standalone `pio fleet` daemon — same scrape
        # loop, federating onto the dashboard's own registry
        import os as _os

        from pio_tpu.obs.fleet import (
            TARGETS_ENV, FleetAggregator, parse_targets,
        )

        spec = (fleet_targets if fleet_targets is not None
                else _os.environ.get(TARGETS_ENV, ""))
        targets = parse_targets(spec)
        self.fleet: Optional[FleetAggregator] = (
            FleetAggregator(targets, registry=self.obs)
            if targets else None
        )
        self.router = Router()
        self.router.add("GET", "/", self.index)
        self.router.add("GET", "/instances\\.json", self.list_json)
        self.router.add("GET", "/instances/([^/]+)\\.json", self.get_json)
        self.router.add("GET", "/instances/([^/]+)\\.html", self.get_html)
        self.router.add("GET", "/serving\\.html", self.serving)
        self.router.add("GET", "/fleet\\.html", self.fleet_html)
        self.router.add("GET", "/fleet\\.json", self.fleet_json)
        self.router.add("GET", "/training\\.html", self.training_html)
        self.router.add("GET", "/devices\\.html", self.devices_html)
        self.router.add("GET", "/metrics", self.get_metrics)
        self.router.add("GET", "/logs\\.json", self.get_logs)
        self.router.add("GET", "/healthz", self.healthz)
        self.router.add("GET", "/readyz", self.readyz)

    def _completed(self):
        return Storage.get_meta_data_evaluation_instances().get_completed()

    def index(self, req: Request) -> Tuple[int, Any]:
        self._pageviews.inc(page="index")
        rows = []
        for i in self._completed():
            rows.append(
                "<tr>"
                f"<td><a href='/instances/{_html.escape(i.id)}.html'>"
                f"{_html.escape(i.id)}</a></td>"
                f"<td>{_html.escape(i.evaluation_class)}</td>"
                f"<td>{_html.escape(i.start_time.isoformat())}</td>"
                f"<td>{_html.escape(i.end_time.isoformat())}</td>"
                f"<td>{_html.escape(i.evaluator_results)}</td>"
                "</tr>"
            )
        page = (
            "<!doctype html><html><head><title>pio-tpu dashboard</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:.4em .8em;text-align:left}</style></head><body>"
            "<h1>Evaluation Dashboard</h1>"
            "<p><a href='/serving.html'>serving metrics</a> &middot; "
            "<a href='/fleet.html'>fleet</a> &middot; "
            "<a href='/training.html'>training</a> &middot; "
            "<a href='/devices.html'>devices</a></p>"
            "<table><tr><th>Instance</th><th>Evaluation</th><th>Start</th>"
            "<th>End</th><th>Result</th></tr>"
            + "".join(rows)
            + "</table></body></html>"
        )
        return 200, _html_response(page)

    def list_json(self, req: Request) -> Tuple[int, Any]:
        return 200, [_instance_summary(i) for i in self._completed()]

    def _find(self, instance_id: str):
        return Storage.get_meta_data_evaluation_instances().get(instance_id)

    def get_json(self, req: Request) -> Tuple[int, Any]:
        inst = self._find(req.path_args[0])
        if inst is None:
            return 404, {"message": "evaluation instance not found"}
        out = _instance_summary(inst)
        try:
            out["results"] = json.loads(inst.evaluator_results_json or "null")
        except json.JSONDecodeError:
            out["results"] = None
        return 200, out

    def get_html(self, req: Request) -> Tuple[int, Any]:
        inst = self._find(req.path_args[0])
        if inst is None:
            return 404, {"message": "evaluation instance not found"}
        body = inst.evaluator_results_html or (
            "<html><body><pre>"
            + _html.escape(inst.evaluator_results_json or "(no results)")
            + "</pre></body></html>"
        )
        return 200, _html_response(body)

    # -- health/logs (ISSUE 2) ----------------------------------------------
    def _check_storage_ready(self):
        Storage.get_meta_data_evaluation_instances()
        return True, "metadata store reachable"

    def healthz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.readiness()
        return (200 if ok else 503), report

    def get_logs(self, req: Request) -> Tuple[int, Any]:
        n = int_param(req.params, "n", 100, lo=0, hi=slog.ring().cap)
        try:
            return 200, slog.logs_payload(
                n=n,
                level=req.params.get("level"),
                trace_id=req.params.get("trace_id"),
                logger=req.params.get("logger"),
            )
        except ValueError as e:
            raise HTTPError(400, str(e))

    # -- serving observability (ISSUE 1) ------------------------------------
    def get_metrics(self, req: Request) -> Tuple[int, Any]:
        return 200, metrics_response(self.obs.render())

    def _scrape_query_server(self) -> Tuple[Optional[ParsedMetrics],
                                            Optional[dict], str]:
        """(parsed /metrics, / status JSON, error message)."""
        import urllib.request

        try:
            with urllib.request.urlopen(
                self.query_url + "/metrics", timeout=3.0
            ) as r:
                pm = parse_prometheus_text(r.read().decode("utf-8"))
            with urllib.request.urlopen(self.query_url + "/", timeout=3.0) as r:
                status = json.loads(r.read().decode("utf-8"))
            return pm, status, ""
        except Exception as e:
            return None, None, f"{type(e).__name__}: {e}"

    def _fetch_json(self, path: str) -> Optional[dict]:
        """Best-effort GET of a query-server JSON endpoint (None on any
        failure — the serving page degrades panel-by-panel)."""
        import urllib.request

        try:
            with urllib.request.urlopen(
                self.query_url + path, timeout=3.0
            ) as r:
                return json.loads(r.read().decode("utf-8"))
        except Exception:
            return None

    def _slo_panel(self) -> str:
        """SLO/error-budget table from the query server's /slo.json."""
        data = self._fetch_json("/slo.json")
        if not data or not data.get("slos"):
            return (
                "<h2>SLOs</h2><p>none configured "
                "(<code>pio deploy --slo p99=50ms:99.9</code>)</p>"
            )
        rows = []
        for s in data["slos"]:
            firing = [a["severity"] for a in s.get("alerts", []) if a["firing"]]
            burns = s.get("burnRates", {})
            fast = burns.get("300s")
            slow = burns.get("3600s")
            rows.append(
                f"<tr><td>{_html.escape(s['name'])}</td>"
                f"<td>{s['objective'] * 100:.3g}%</td>"
                f"<td>{int(s['total'])}</td><td>{int(s['errors'])}</td>"
                f"<td>{s['errorBudgetRemaining'] * 100:.1f}%</td>"
                f"<td>{fast if fast is not None else 'n/a'}</td>"
                f"<td>{slow if slow is not None else 'n/a'}</td>"
                f"<td>{_html.escape(', '.join(firing) or '-')}</td></tr>"
            )
        return (
            "<h2>SLOs</h2>"
            "<table><tr><th>objective</th><th>target</th><th>requests</th>"
            "<th>errors</th><th>budget left</th><th>burn 5m</th>"
            "<th>burn 1h</th><th>alerts</th></tr>"
            + "".join(rows) + "</table>"
        )

    def _qos_panel(self) -> str:
        """Admission-control panel from the query server's /qos.json
        (ISSUE 3): shed counts by reason, token-bucket level, queue and
        inflight occupancy, breaker states."""
        data = self._fetch_json("/qos.json")
        if not data or not data.get("enabled"):
            return (
                "<h2>QoS</h2><p>admission control off "
                "(<code>pio deploy --qos 'rps=500,queue=64,"
                "deadline=100ms'</code>)</p>"
            )
        shed = data.get("shed", {})
        shed_rows = "".join(
            f"<tr><td>{_html.escape(reason)}</td><td>{int(n)}</td></tr>"
            for reason, n in sorted(shed.items())
        )
        parts = [
            "<h2>QoS</h2>",
            f"<p>admitted (pool-wide): {int(data.get('admitted', 0))}"
            f" &middot; degraded (stale-cache): "
            f"{int(data.get('degraded', 0))}</p>",
            "<table><tr><th>shed reason</th><th>count</th></tr>"
            + (shed_rows or "<tr><td colspan='2'>none</td></tr>")
            + "</table>",
        ]
        bucket = data.get("bucket")
        if bucket:
            parts.append(
                f"<p>engine bucket: {bucket['tokens']:.1f} / "
                f"{bucket['burst']:.0f} tokens "
                f"(refill {bucket['rate']:.0f}/s)</p>"
            )
        conc = data.get("concurrency")
        if conc:
            parts.append(
                f"<p>concurrency: {conc['inflight']}/{conc['maxInflight']} "
                f"inflight, {conc['queued']}/{conc['maxQueue']} queued</p>"
            )
        breakers = data.get("breakers") or {}
        if breakers:
            rows = "".join(
                f"<tr><td>{_html.escape(dep)}</td>"
                f"<td>{_html.escape(b['state'])}</td>"
                f"<td>{b['windowFailures']}/{b['windowSamples']}</td></tr>"
                for dep, b in sorted(breakers.items())
            )
            parts.append(
                "<table><tr><th>breaker</th><th>state</th>"
                "<th>failures</th></tr>" + rows + "</table>"
            )
        return "".join(parts)

    def _hotpath_panel(self) -> str:
        """Latency-attribution waterfall from /debug/hotpath.json: the
        per-stage budget of the average request and how much of the e2e
        latency the stages attribute (the residual is the
        instrumentation's blind spot)."""
        data = self._fetch_json("/debug/hotpath.json")
        if not data or not data.get("stages"):
            return (
                "<h2>Hot-path budget</h2><p>no attributed requests yet "
                "(<code>GET /debug/hotpath.json</code>)</p>"
            )
        fmt = lambda v: f"{v:.3f}" if v is not None else "n/a"
        entries = [(s, "") for s in data["stages"]] + [
            (s, "&nbsp;&nbsp;&#8627; ") for s in data.get("substages", [])
        ]
        rows = "".join(
            f"<tr><td>{indent}{_html.escape(s['stage'])}</td>"
            f"<td>{s['count']}</td>"
            f"<td>{fmt(s.get('avgMs'))}</td><td>{fmt(s.get('p50Ms'))}</td>"
            f"<td>{fmt(s.get('p95Ms'))}</td></tr>"
            for s, indent in entries
        )
        frac = data.get("attributedFraction")
        e2e = data.get("e2e") or {}
        budget_line = (
            f"<p>e2e avg {fmt(e2e.get('avgMs'))} ms &middot; attributed "
            f"{fmt(data.get('attributedMsPerRequest'))} ms"
            + (f" ({frac * 100:.1f}%)" if frac is not None else "")
            + f" &middot; residual {fmt(data.get('residualMsPerRequest'))}"
            f" ms over {data.get('requestCount', 0)} requests</p>"
        )
        return (
            "<h2>Hot-path budget</h2>" + budget_line
            + "<table><tr><th>stage</th><th>count</th>"
            "<th>avg/req</th><th>p50</th><th>p95</th></tr>"
            + rows + "</table>"
        )

    def _log_panel(self, n: int = 25) -> str:
        """Live tail of the query server's structured log ring."""
        data = self._fetch_json(f"/logs.json?n={n}")
        if not data or not data.get("logs"):
            return "<h2>Recent logs</h2><p>no log entries</p>"
        lines = []
        for e in data["logs"]:
            trace = f" [{e['trace_id']}]" if e.get("trace_id") else ""
            lines.append(_html.escape(
                f"{e.get('ts', '')} {e.get('level', ''):7s}"
                f"{trace} {e.get('logger', '')}: {e.get('msg', '')}"
            ))
        return (
            "<h2>Recent logs</h2><pre style='background:#f6f6f6;"
            "padding:1em;overflow-x:auto'>" + "\n".join(lines) + "</pre>"
        )

    # -- fleet federation (ISSUE 11) ----------------------------------------
    def fleet_json(self, req: Request) -> Tuple[int, Any]:
        if self.fleet is None:
            return 404, {
                "message": "no fleet configured (set PIO_TPU_FLEET_TARGETS "
                           "or run `pio fleet --targets ...`)"
            }
        return 200, self.fleet.fleet_payload()

    def fleet_html(self, req: Request) -> Tuple[int, Any]:
        """Fleet panel: member liveness table, partlog replication lag,
        worst SLO burn per objective, and engine placement — rendered
        from the embedded aggregator's last scrape pass."""
        self._pageviews.inc(page="fleet")
        head = (
            "<!doctype html><html><head><title>pio-tpu fleet</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse;margin-bottom:1em}"
            "td,th{border:1px solid #ccc;padding:.4em .8em;"
            "text-align:left}.up{color:#080}.stale{color:#a60}"
            ".down{color:#a00}</style></head><body><h1>Fleet</h1>"
        )
        if self.fleet is None:
            return 200, _html_response(
                head + "<p>no fleet configured — set "
                "<code>PIO_TPU_FLEET_TARGETS=host:port,...</code> or run "
                "<code>pio fleet --targets ...</code></p></body></html>"
            )
        pay = self.fleet.fleet_payload()
        f = pay["fleet"]
        summary = (
            f"<p>{f['members']} members: "
            f"<span class='up'>{f['up']} up</span>, "
            f"<span class='stale'>{f['stale']} stale</span>, "
            f"<span class='down'>{f['down']} down</span> "
            f"(scrape every {f['scrapeIntervalSeconds']:.1f}s)</p>"
        )
        member_rows = "".join(
            f"<tr><td>{_html.escape(m['member'])}</td>"
            f"<td class='{_html.escape(m['status'])}'>"
            f"{_html.escape(m['status'])}</td>"
            f"<td>{_html.escape(m['role'])}</td>"
            f"<td>{'yes' if m['ready'] else 'no' if m['ready'] is False else '?'}</td>"
            f"<td>{m['scrapeAgeSeconds'] if m['scrapeAgeSeconds'] is not None else 'never'}</td>"
            f"<td>{m['scrapeErrors']}</td>"
            f"<td>{_html.escape(m['lastError'] or '-')}</td></tr>"
            for m in pay["members"]
        )
        members = (
            "<h2>Members</h2><table><tr><th>member</th><th>status</th>"
            "<th>role</th><th>ready</th><th>scrape age (s)</th>"
            "<th>errors</th><th>last error</th></tr>"
            + member_rows + "</table>"
        )
        lag_rows = []
        for leader in pay["partlog"]["leaders"]:
            for part in leader["partitionDetail"]:
                for fol in part["followers"]:
                    lag_rows.append(
                        f"<tr><td>{_html.escape(str(leader['member']))}</td>"
                        f"<td>{part['partition']}</td>"
                        f"<td>{_html.escape(str(fol['follower']))}</td>"
                        f"<td>{part['committedBytes']}</td>"
                        f"<td>{fol['ackedBytes'] if fol['ackedBytes'] is not None else 'n/a'}</td>"
                        f"<td>{fol['lagBytes'] if fol['lagBytes'] is not None else 'n/a'}</td>"
                        f"<td>{'yes' if fol['connected'] else 'no'}</td></tr>"
                    )
        lag = (
            "<h2>Replication lag</h2>"
            + ("<table><tr><th>leader</th><th>partition</th>"
               "<th>follower</th><th>committed</th><th>acked</th>"
               "<th>lag (bytes)</th><th>connected</th></tr>"
               + "".join(lag_rows) + "</table>"
               if lag_rows else "<p>no replicated partlog members</p>")
        )
        burn_rows = "".join(
            f"<tr><td>{_html.escape(name)}</td>"
            f"<td>{_html.escape(str(w['member']))}</td>"
            f"<td>{w['burn']}</td>"
            f"<td>{_html.escape(str(w['window']))}</td>"
            f"<td>{_html.escape(', '.join(w['firing']) or '-')}</td></tr>"
            for name, w in sorted(pay["slo"]["worstBurn"].items())
        )
        slo = (
            "<h2>Worst SLO burn per objective</h2>"
            + ("<table><tr><th>objective</th><th>worst member</th>"
               "<th>burn</th><th>window</th><th>firing</th></tr>"
               + burn_rows + "</table>"
               if burn_rows else "<p>no SLOs reported</p>")
        )
        place_rows = "".join(
            f"<tr><td>{_html.escape(p['member'])}</td>"
            f"<td>{_html.escape(p['mode'])}</td>"
            f"<td>{p['paramBytes']}</td>"
            f"<td>{_html.escape(', '.join(str(sc['name']) for sc in p['scorers']) or '-')}</td></tr>"
            for p in pay["placement"]
        )
        placement = (
            "<h2>Placement</h2>"
            + ("<table><tr><th>member</th><th>mode</th>"
               "<th>param bytes</th><th>scorers</th></tr>"
               + place_rows + "</table>"
               if place_rows else "<p>no serving members reporting</p>")
        )
        return 200, _html_response(
            head + summary + members + lag + slo + placement
            + "<p><a href='/fleet.json'>/fleet.json</a> — the router "
            "contract</p></body></html>"
        )

    # -- training telemetry (ISSUE 16) --------------------------------------
    def training_html(self, req: Request) -> Tuple[int, Any]:
        """Live training view: one scrape of a trainer status sidecar's
        /train.json — run/phase header, step progress with ETA, the
        recent-loss window, and stream/phase breakdowns."""
        self._pageviews.inc(page="training")
        url = (req.params.get("url") or self.train_url).rstrip("/")
        head = (
            "<!doctype html><html><head><title>pio-tpu training</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse;margin-bottom:1em}"
            "td,th{border:1px solid #ccc;padding:.4em .8em;"
            "text-align:right}th,td:first-child{text-align:left}"
            ".bar{background:#dfd;display:inline-block;height:1em}"
            "</style></head><body><h1>Training</h1>"
        )
        if not url:
            return 200, _html_response(
                head + "<p>no trainer configured — start "
                "<code>pio train</code> (its status sidecar prints a "
                "loopback port) and pass <code>--train-url</code>, set "
                "<code>PIO_TPU_TRAIN_STATUS_URL</code>, or use "
                "<code>?url=http://127.0.0.1:PORT</code></p></body></html>"
            )
        import urllib.request

        try:
            with urllib.request.urlopen(url + "/train.json", timeout=3.0) as r:
                data = json.loads(r.read().decode("utf-8"))
        except Exception as e:
            return 200, _html_response(
                head + f"<p>scraping <code>{_html.escape(url)}"
                "/train.json</code> (override with ?url=)</p>"
                f"<p>scrape failed: {_html.escape(f'{type(e).__name__}: {e}')}"
                " — no run in flight, or the sidecar exited with its "
                "run</p></body></html>"
            )
        fmt = lambda v, spec="{:.3f}": (
            spec.format(v) if isinstance(v, (int, float)) else "n/a"
        )
        progress = data.get("progress")
        pct = progress * 100 if isinstance(progress, (int, float)) else None
        bar = (
            f"<p><span class='bar' style='width:{pct:.0f}%'>&nbsp;</span>"
            f" {pct:.1f}%</p>" if pct is not None else ""
        )
        summary = (
            f"<p>run <code>{_html.escape(str(data.get('runId') or '?'))}</code>"
            f" &middot; engine <code>"
            f"{_html.escape(str(data.get('engineId') or '?'))}</code>"
            f" &middot; phase <b>{_html.escape(str(data.get('phase') or '?'))}"
            f"</b> &middot; algo "
            f"{_html.escape(str(data.get('algo') or '-'))}</p>" + bar
            + "<table><tr><th>step</th><th>of</th><th>epoch</th>"
            "<th>examples</th><th>examples/s</th><th>loss</th>"
            "<th>eta (s)</th><th>elapsed (s)</th></tr>"
            f"<tr><td>{data.get('step', 0)}</td>"
            f"<td>{data.get('totalSteps', 0)}</td>"
            f"<td>{fmt(data.get('epoch'), '{:.2f}')}</td>"
            f"<td>{data.get('examples', 0)}</td>"
            f"<td>{fmt(data.get('examplesPerSecond'), '{:.0f}')}</td>"
            f"<td>{fmt(data.get('loss'), '{:.5f}')}</td>"
            f"<td>{fmt(data.get('etaSeconds'), '{:.0f}')}</td>"
            f"<td>{fmt(data.get('elapsedSeconds'), '{:.1f}')}</td></tr>"
            "</table>"
        )
        window = data.get("lossWindow") or []
        losses = (
            "<h2>Loss window</h2><pre style='background:#f6f6f6;"
            "padding:1em;overflow-x:auto'>"
            + _html.escape(" ".join(f"{v:.5f}" for v in window))
            + "</pre>" if window else ""
        )
        stream = data.get("stream") or {}
        stream_table = (
            "<h2>Stream feed</h2><table>"
            "<tr><th>streamed</th><th>chunks</th><th>h2d bytes</th>"
            "<th>overlap ratio</th></tr>"
            f"<tr><td>{'yes' if stream.get('streamed') else 'no'}</td>"
            f"<td>{stream.get('chunks', 0)}</td>"
            f"<td>{stream.get('h2dBytes', 0)}</td>"
            f"<td>{fmt(stream.get('overlapRatio'))}</td></tr></table>"
        )
        phases = data.get("phases") or {}
        phase_rows = "".join(
            f"<tr><td>{_html.escape(k)}</td><td>{fmt(v)}</td></tr>"
            for k, v in phases.items()
        )
        phase_table = (
            "<h2>Phases (s)</h2><table><tr><th>phase</th><th>seconds</th>"
            "</tr>" + phase_rows + "</table>" if phase_rows else ""
        )
        return 200, _html_response(
            head + f"<p>scraping <code>{_html.escape(url)}/train.json</code>"
            " (override with ?url=)</p>" + summary + losses + stream_table
            + phase_table + "</body></html>"
        )

    # -- device telemetry (ISSUE 17) -----------------------------------------
    def devices_html(self, req: Request) -> Tuple[int, Any]:
        """Live device view: one scrape of a /device.json surface (query
        server by default, trainer sidecar via ?url=) — per-device HBM
        table, compile-site attribution, and the placement ledger."""
        self._pageviews.inc(page="devices")
        url = (req.params.get("url") or self.query_url or self.train_url)
        url = url.rstrip("/") if url else ""
        head = (
            "<!doctype html><html><head><title>pio-tpu devices</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse;margin-bottom:1em}"
            "td,th{border:1px solid #ccc;padding:.4em .8em;"
            "text-align:right}th,td:first-child{text-align:left}"
            "</style></head><body><h1>Devices</h1>"
        )
        if not url:
            return 200, _html_response(
                head + "<p>no /device.json source configured — pass "
                "<code>--query-url</code> / <code>--train-url</code> or "
                "use <code>?url=http://127.0.0.1:PORT</code></p>"
                "</body></html>"
            )
        import urllib.request

        try:
            with urllib.request.urlopen(
                url + "/device.json", timeout=3.0
            ) as r:
                data = json.loads(r.read().decode("utf-8"))
        except Exception as e:
            return 200, _html_response(
                head + f"<p>scraping <code>{_html.escape(url)}"
                "/device.json</code> (override with ?url=)</p>"
                f"<p>scrape failed: {_html.escape(f'{type(e).__name__}: {e}')}"
                "</p></body></html>"
            )
        mb = lambda v: (
            f"{v / 1048576.0:,.1f}" if isinstance(v, (int, float)) else "n/a"
        )
        budget = data.get("budgetBytes") or 0
        headroom = data.get("headroomBytes")
        summary = (
            f"<p>mode <b>{_html.escape(str(data.get('mode') or '?'))}</b>"
            f" &middot; generation {data.get('generation', 0)}"
            f" &middot; samples {data.get('samples', 0)}"
            f" &middot; budget {mb(budget) if budget else 'unset'} MiB"
            + (f" &middot; headroom <b>{mb(headroom)}</b> MiB"
               if headroom is not None else "")
            + "</p>"
        )
        dev_rows = "".join(
            f"<tr><td>{d.get('device')}</td>"
            f"<td>{mb(d.get('bytesInUse'))}</td>"
            f"<td>{mb(d.get('peakBytes'))}</td>"
            f"<td>{mb(d.get('limitBytes'))}</td>"
            f"<td>{mb(d.get('ledgerBytes'))}</td>"
            f"<td>{mb(d.get('driftBytes'))}</td>"
            f"<td>{_html.escape(str(d.get('source') or '-'))}</td></tr>"
            for d in data.get("devices") or []
        )
        devices = (
            "<h2>HBM (MiB)</h2><table><tr><th>device</th><th>in use</th>"
            "<th>peak</th><th>limit</th><th>ledger</th><th>drift</th>"
            "<th>source</th></tr>" + dev_rows + "</table>"
            if dev_rows else "<p>no device samples yet</p>"
        )
        compiles = data.get("compiles") or {}
        site_rows = "".join(
            f"<tr><td>{_html.escape(site)}</td><td>{row.get('count', 0)}</td>"
            f"<td>{row.get('seconds', 0.0):.3f}</td>"
            f"<td>{_html.escape(str(row.get('lastTraceId') or '-'))}</td>"
            "</tr>"
            for site, row in sorted((compiles.get("sites") or {}).items())
        )
        compile_table = (
            f"<h2>Compiles (total {compiles.get('total', 0)})</h2>"
            "<table><tr><th>site</th><th>count</th><th>seconds</th>"
            "<th>last trace</th></tr>" + site_rows + "</table>"
            if site_rows else "<p>no compiles attributed yet</p>"
        )
        ledger = data.get("ledger") or {}
        place_rows = "".join(
            f"<tr><td>{_html.escape(str(p.get('name') or p.get('key')))}</td>"
            f"<td>{_html.escape(str(p.get('category')))}</td>"
            f"<td>{p.get('generation') if p.get('generation') is not None else '-'}</td>"
            f"<td>{mb(p.get('bytes'))}</td></tr>"
            for p in data.get("placements") or []
        )
        placements = (
            f"<h2>Placements (ledger {mb(ledger.get('totalBytes'))} MiB)</h2>"
            "<table><tr><th>name</th><th>category</th><th>gen</th>"
            "<th>MiB</th></tr>" + place_rows + "</table>"
            if place_rows else ""
        )
        return 200, _html_response(
            head + f"<p>scraping <code>{_html.escape(url)}/device.json</code>"
            " (override with ?url=)</p>" + summary + devices + compile_table
            + placements + "</body></html>"
        )

    def serving(self, req: Request) -> Tuple[int, Any]:
        """Live serving view: pool-wide request totals + avg QPS since
        deploy and a per-stage latency table, from one scrape of the
        query server (any pool worker answers with pool-wide sums)."""
        self._pageviews.inc(page="serving")
        url = req.params.get("url") or self.query_url
        if url != self.query_url:
            self.query_url = url.rstrip("/")
        pm, status, err = self._scrape_query_server()
        head = (
            "<!doctype html><html><head><title>pio-tpu serving</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:.4em .8em;text-align:right}th,td:first-child"
            "{text-align:left}</style></head><body>"
            "<h1>Serving</h1>"
            f"<p>scraping <code>{_html.escape(self.query_url)}"
            "/metrics</code> (override with ?url=)</p>"
        )
        if pm is None:
            return 200, _html_response(
                head + f"<p>scrape failed: {_html.escape(err)}</p>"
                "</body></html>"
            )
        total = sum(pm.family("pio_tpu_queries_total").values())
        errors = sum(pm.family("pio_tpu_query_errors_total").values())
        qps = None
        if status and status.get("startTime"):
            import datetime as _dt

            try:
                t0 = _dt.datetime.fromisoformat(status["startTime"])
                up = (_dt.datetime.now(_dt.timezone.utc) - t0).total_seconds()
                if up > 0:
                    qps = total / up
            except ValueError:
                pass
        summary = (
            "<table><tr><th>requests</th><th>errors</th>"
            "<th>avg QPS since deploy</th></tr>"
            f"<tr><td>{int(total)}</td><td>{int(errors)}</td>"
            f"<td>{f'{qps:.2f}' if qps is not None else 'n/a'}</td></tr>"
            "</table>"
        )
        # per-stage latency table from the stage histograms (pool-wide)
        stages: dict = {}
        for ls, count in pm.family("pio_tpu_query_stage_seconds_count").items():
            d = dict(ls)
            stage = d.get("stage", "?")
            total_s = pm.value("pio_tpu_query_stage_seconds_sum", **d) or 0.0
            row = {
                "count": int(count),
                "avgMs": (total_s / count * 1e3) if count else None,
            }
            for col, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
                v = pm.histogram_quantile("pio_tpu_query_stage_seconds", q, **d)
                row[col] = v * 1e3 if v is not None else None
            stages[stage] = row
        fmt = lambda v: f"{v:.3f}" if v is not None else "n/a"
        stage_rows = "".join(
            f"<tr><td>{_html.escape(stage)}</td><td>{r['count']}</td>"
            f"<td>{fmt(r['avgMs'])}</td><td>{fmt(r['p50'])}</td>"
            f"<td>{fmt(r['p95'])}</td><td>{fmt(r['p99'])}</td></tr>"
            for stage, r in sorted(stages.items())
        )
        stage_table = (
            "<h2>Per-stage latency (ms)</h2>"
            "<table><tr><th>stage</th><th>count</th><th>avg</th>"
            "<th>p50</th><th>p95</th><th>p99</th></tr>"
            + (stage_rows or "<tr><td colspan='6'>no observations</td></tr>")
            + "</table>"
        )
        return 200, _html_response(
            head + summary + stage_table + self._hotpath_panel()
            + self._slo_panel() + self._qos_panel() + self._log_panel()
            + "</body></html>"
        )


def create_dashboard(
    host: str = "0.0.0.0", port: int = 9000,
    query_url: str = "http://127.0.0.1:8000",
    fleet_targets: Optional[str] = None,
    train_url: Optional[str] = None,
) -> JsonHTTPServer:
    """Build (unstarted) dashboard — reference ``Dashboard.main``. When
    fleet targets are configured the embedded aggregator's scrape loop
    starts here (daemon thread; it dies with the process)."""
    service = DashboardService(
        query_url=query_url, fleet_targets=fleet_targets,
        train_url=train_url,
    )
    server = JsonHTTPServer(
        service.router, host, port, name="pio-tpu-dashboard"
    )
    server.service = service
    if service.fleet is not None:
        service.fleet.start()
    return server
