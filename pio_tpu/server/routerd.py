"""Serving-router daemon — the front tier of the serving fabric.

``pio route --targets host:port,...`` builds a :class:`RouterService`:
a :class:`~pio_tpu.router.core.ServingRouter` fanning query traffic
across the member list, fed by an embedded
:class:`~pio_tpu.obs.fleet.FleetAggregator` scraping the same members
(tightened staleness thresholds — a front tier must see a dead member
within two scrape intervals, not the dashboard-grade five).

Routes:

- ``POST /queries.json`` — the relay. Speaks both wires: JSON bodies
  relay as their original bytes (no re-serialize), and the packed int8
  wire (``application/x-pio-query-i8``) passes ``req.packed`` through
  untouched under the ``# pio: hotpath=zerocopy`` contract. Entity
  affinity comes from the JSON body's entity field when present; the
  packed frame carries no entity id, so those spread by load. Upstream
  status codes relay as-is; router-side refusals use the QoS
  vocabulary (503 + ``Retry-After``) and every reply carries
  ``X-Pio-Router-Member`` naming the member that answered.
- ``GET /router.json`` — ring membership, per-member health/burn/lag/
  generation and forward counters (schema in docs/observability.md);
- ``POST /deploy`` — admin (bearer key or loopback): manifest-verified
  rollout of one instance to every member (see
  :mod:`pio_tpu.router.deploy`);
- ``POST /rollout`` / ``POST /rollout/abort`` / ``GET /rollout.json`` —
  progressive delivery (see :mod:`pio_tpu.router.rollout`): start a
  shadow->canary->promote rollout of a candidate instance, abort it,
  or read the live stage + decision trail;
- ``GET /fleet.json`` — the embedded aggregator's federated payload;
- ``GET /metrics`` / ``/healthz`` / ``/readyz`` — ready once one full
  scrape pass has completed (never steer by an empty snapshot).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple

from pio_tpu.faults import exposition_lines as fault_lines
from pio_tpu.obs import HealthMonitor, MetricsRegistry, slog
from pio_tpu.obs.fleet import FleetAggregator
from pio_tpu.qos.gate import retry_after_header
from pio_tpu.qos.policy import PRIORITY_HEADER
from pio_tpu.router.core import ServingRouter, Shed
from pio_tpu.router.deploy import load_manifest, push_deploy
from pio_tpu.router.rollout import (
    RolloutConfig,
    RolloutController,
    RolloutMetrics,
)
from pio_tpu.server.http import (
    HTTPError,
    JsonHTTPServer,
    RawResponse,
    Request,
    Router,
    keys_equal,
    metrics_response,
)

#: JSON body fields probed (in order) for the affinity entity id —
#: the reference engines key queries by user.
ENTITY_FIELDS = ("entityId", "user", "uid", "userId")

#: staleness thresholds in scrape intervals for the EMBEDDED aggregator:
#: a member whose scrape age passes 2 intervals is down to the router
#: (the fleet dashboard default of 5 is built for humans, not failover).
STALE_AFTER_INTERVALS = 1.6
DOWN_AFTER_INTERVALS = 2.0


def entity_of(body: Any) -> Optional[str]:
    """The affinity key of a JSON query body, if it names one."""
    if not isinstance(body, dict):
        return None
    for field in ENTITY_FIELDS:
        v = body.get(field)
        if isinstance(v, (str, int)):
            return str(v)
    return None


class RouterService:
    """Router core + scraper + routes; ``create_router_server`` wires
    it to a port."""

    def __init__(
        self,
        targets: List[Tuple[str, str]],
        partitions: Optional[int] = None,
        interval_s: Optional[float] = None,
        admin_key: Optional[str] = None,
        timeout_s: float = 5.0,
        fetch=None,
    ):
        if not targets:
            raise ValueError(
                "router needs at least one member target "
                "(--targets host:port,... or PIO_TPU_FLEET_TARGETS)"
            )
        self.admin_key = admin_key
        self.obs = MetricsRegistry()
        slog.install()
        self.obs.add_collector(slog.exposition_lines)
        self.obs.add_collector(fault_lines)
        self.agg = FleetAggregator(
            targets,
            registry=self.obs,
            interval_s=interval_s,
            stale_after_s=None,
            down_after_s=None,
            fetch=fetch,
        )
        # tighten the staleness machine to failover grade (the ctor
        # computed dashboard-grade defaults from the interval)
        self.agg.stale_after_s = STALE_AFTER_INTERVALS * self.agg.interval_s
        self.agg.down_after_s = DOWN_AFTER_INTERVALS * self.agg.interval_s
        self.core = ServingRouter(
            targets,
            registry=self.obs,
            partitions=partitions,
            timeout_s=timeout_s,
            forced_down_s=DOWN_AFTER_INTERVALS * self.agg.interval_s,
        )
        self._stop = threading.Event()
        self._ingest_thread: Optional[threading.Thread] = None
        self._seen_passes = 0
        self.rollout_metrics = RolloutMetrics(self.obs)
        self.rollout: Optional[RolloutController] = None
        self._rollout_count = 0
        self.health = HealthMonitor()
        self.health.add_readiness("first_scrape", self._check_first_scrape)
        self.router = Router()
        self.router.add("GET", "/", self.index)
        self.router.add("POST", "/queries\\.json", self.relay_query)
        self.router.add("GET", "/router\\.json", self.router_json)
        self.router.add("GET", "/fleet\\.json", self.fleet_json)
        self.router.add("POST", "/deploy", self.deploy)
        self.router.add("POST", "/rollout", self.start_rollout)
        self.router.add("POST", "/rollout/abort", self.abort_rollout)
        self.router.add("POST", "/rollout/approve", self.approve_rollout)
        self.router.add("GET", "/rollout\\.json", self.rollout_json)
        self.router.add("GET", "/metrics", self.get_metrics)
        self.router.add("GET", "/healthz", self.healthz)
        self.router.add("GET", "/readyz", self.readyz)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the scrape loop and the ingest pump (payload -> core
        after every completed scrape pass)."""
        self.agg.start()
        if self._ingest_thread is not None:
            return
        self._stop.clear()
        self._ingest_thread = threading.Thread(
            target=self._ingest_loop, name="router-ingest", daemon=True
        )
        self._ingest_thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._ingest_thread = self._ingest_thread, None
        if t is not None:
            t.join(timeout=2.0)
        ro = self.rollout
        if ro is not None:
            ro.stop()
        self.agg.stop()
        self.core.close()

    def _ingest_loop(self) -> None:
        poll = min(0.5, self.agg.interval_s / 4.0)
        while not self._stop.is_set():
            passes = self.agg.passes
            if passes != self._seen_passes:
                self._seen_passes = passes
                try:
                    self.core.ingest_fleet(self.agg.fleet_payload())
                except Exception:  # an ingest must never kill the pump
                    pass
            if self._stop.wait(poll):
                return

    def _check_first_scrape(self):
        if self.agg.passes < 1:
            return False, "no scrape pass completed yet"
        return True, f"{self.agg.passes} scrape passes"

    def _check_admin(self, req: Request) -> None:
        if self.admin_key is not None:
            if not keys_equal(req.bearer_key(), self.admin_key):
                raise HTTPError(401, "invalid admin accessKey")
        elif req.client_addr not in ("127.0.0.1", "::1"):
            raise HTTPError(
                403, "admin routes are loopback-only without an admin key"
            )

    # -- relay -------------------------------------------------------------
    def relay_query(self, req: Request):  # pio: hotpath=zerocopy
        """Both wires, one relay: the packed frame (or the JSON body's
        original bytes) goes member-ward untouched."""
        priority = req.headers.get(PRIORITY_HEADER.lower(), "")
        if req.packed is not None:
            out_body = req.packed   # zero-copy: bytes or memoryview
            entity = None
        else:
            out_body = req.raw_body
            entity = entity_of(req.body)
        try:
            status, reply, upstream_body, member = self.core.forward(
                "POST", "/queries.json", out_body, req.headers,
                entity_id=entity, priority=priority,
            )
        except Shed as s:
            raise HTTPError(
                s.status,
                f"router shed: {s.reason}",
                headers=retry_after_header(s.retry_after_s),
            ) from s
        ctype = reply.pop(
            "Content-Type", "application/json; charset=UTF-8"
        )
        reply["X-Pio-Router-Member"] = member
        return status, RawResponse(
            upstream_body, content_type=ctype, headers=reply
        )

    # -- admin / introspection ---------------------------------------------
    def deploy(self, req: Request) -> Tuple[int, Any]:
        """Manifest-verified rollout: push the instance's shard manifest
        to every member's ``/deploy.json``; only verified members get
        their generation flipped into rotation."""
        self._check_admin(req)
        body = req.body if isinstance(req.body, dict) else {}
        instance_id = body.get("engineInstanceId")
        if not instance_id:
            raise HTTPError(400, "engineInstanceId is required")
        from pio_tpu.storage import Storage

        try:
            manifest = load_manifest(
                Storage.get_model_data_models(), instance_id
            )
        except Exception as e:
            raise HTTPError(
                502, f"cannot read shard manifest: {e}"
            ) from e
        results = []
        verified = 0
        for ms in self.core.ring_members():
            outcome, detail = push_deploy(
                ms.base_url, instance_id, manifest,
                timeout_s=max(self.core.timeout_s, 60.0),
                admin_key=self.admin_key,
            )
            self.core.note_deploy(ms.name, instance_id, outcome)
            verified += 1 if outcome == "verified" else 0
            results.append({
                "member": ms.name,
                "outcome": outcome,
                "detail": detail,
            })
        status = 200 if verified == len(results) else 502
        return status, {
            "engineInstanceId": instance_id,
            "sharded": manifest is not None,
            "verified": verified,
            "members": results,
        }

    def start_rollout(self, req: Request) -> Tuple[int, Any]:
        """Kick off a progressive rollout of one candidate instance.

        Body: ``{engineInstanceId, targets: "host:port,...", ...knobs}``
        (knob names match the ``config`` block of ``/rollout.json``).
        409 while another rollout is still live — one candidate at a
        time is the whole point of a judged rollout."""
        self._check_admin(req)
        body = req.body if isinstance(req.body, dict) else {}
        instance_id = body.get("engineInstanceId")
        if not instance_id:
            raise HTTPError(400, "engineInstanceId is required")
        ro = self.rollout
        if ro is not None and ro.active():
            raise HTTPError(
                409,
                f"rollout of {ro.cfg.candidate_instance!r} is still "
                f"{ro.stage}; abort it first (POST /rollout/abort)",
            )
        from pio_tpu.obs.fleet import parse_targets

        targets = parse_targets(body.get("targets") or "")
        cfg = RolloutConfig(
            candidate_instance=str(instance_id),
            candidate_targets=targets,
            incumbent_instance=body.get("incumbentInstance"),
        )
        for key, attr, cast in (
            ("shadowRate", "shadow_rate", float),
            ("shadowMinSamples", "shadow_min_samples", int),
            ("shadowHoldSeconds", "shadow_hold_s", float),
            ("mismatchLimit", "mismatch_limit", float),
            ("scoreTolerance", "score_tolerance", float),
            ("latencyLimitX", "latency_limit_x", float),
            ("canaryFraction", "canary_fraction", float),
            ("canaryHoldSeconds", "canary_hold_s", float),
            ("canaryMinRequests", "canary_min_requests", int),
            ("judgeIntervalSeconds", "judge_interval_s", float),
            ("judgeFastSeconds", "judge_fast_s", float),
            ("judgeSlowSeconds", "judge_slow_s", float),
            ("burnLimit", "burn_limit", float),
            ("availabilityObjective", "availability_objective", float),
            ("downAfterFailures", "down_after_failures", int),
            ("auto", "auto", bool),
        ):
            if body.get(key) is not None:
                setattr(cfg, attr, cast(body[key]))
        try:
            cfg.validate()
        except ValueError as e:
            raise HTTPError(400, str(e)) from e
        self._rollout_count += 1
        controller = RolloutController(
            self.core, cfg, self.rollout_metrics,
            fetch=self._rollout_fetch,
            admin_key=self.admin_key,
            generation=self._rollout_count,
            started_by=body.get("by") or "operator",
        )
        self.rollout = controller
        controller.start()
        return 202, {"rollout": controller.payload()}

    def abort_rollout(self, req: Request) -> Tuple[int, Any]:
        self._check_admin(req)
        ro = self.rollout
        if ro is None:
            raise HTTPError(404, "no rollout has been started")
        ro.abort(by=str(req.client_addr or "operator"))
        return 200, {"rollout": ro.payload()}

    def approve_rollout(self, req: Request) -> Tuple[int, Any]:
        """Release a non-auto rollout's current hold gate."""
        self._check_admin(req)
        ro = self.rollout
        if ro is None:
            raise HTTPError(404, "no rollout has been started")
        ro.approve()
        return 200, {"rollout": ro.payload()}

    def rollout_json(self, req: Request) -> Tuple[int, Any]:
        ro = self.rollout
        if ro is None:
            return 200, {"stage": "idle", "generation": 0, "trail": []}
        return 200, ro.payload()

    @property
    def _rollout_fetch(self):
        # the aggregator's injectable fetch doubles as the controller's
        # (so socketless tests fake both planes with one callable)
        return self.agg._fetch

    def index(self, req: Request) -> Tuple[int, Any]:
        return 200, {
            "service": "pio-tpu-routerd",
            "members": [m.name for m in self.agg.members()],
            "endpoints": [
                "/queries.json", "/router.json", "/fleet.json",
                "/deploy", "/rollout", "/rollout.json", "/metrics",
                "/healthz", "/readyz",
            ],
        }

    def router_json(self, req: Request) -> Tuple[int, Any]:
        snap = self.core.snapshot()
        snap["scrape"] = {
            "intervalSeconds": self.agg.interval_s,
            "staleAfterSeconds": self.agg.stale_after_s,
            "downAfterSeconds": self.agg.down_after_s,
            "passes": self.agg.passes,
        }
        return 200, snap

    def fleet_json(self, req: Request) -> Tuple[int, Any]:
        return 200, self.agg.fleet_payload()

    def get_metrics(self, req: Request) -> Tuple[int, Any]:
        return 200, metrics_response(self.obs.render())

    def healthz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.liveness()
        return (200 if ok else 503), report

    def readyz(self, req: Request) -> Tuple[int, Any]:
        ok, report = self.health.readiness()
        return (200 if ok else 503), report


def create_router_server(
    targets: List[Tuple[str, str]],
    host: str = "0.0.0.0",
    port: int = 8500,
    partitions: Optional[int] = None,
    interval_s: Optional[float] = None,
    admin_key: Optional[str] = None,
    timeout_s: float = 5.0,
    fetch=None,
) -> JsonHTTPServer:
    """Build (unstarted) router daemon; the caller starts the HTTP
    server and then the scrape/ingest loops via ``server.service``."""
    service = RouterService(
        targets,
        partitions=partitions,
        interval_s=interval_s,
        admin_key=admin_key,
        timeout_s=timeout_s,
        fetch=fetch,
    )
    server = JsonHTTPServer(
        service.router, host, port, name="pio-tpu-routerd"
    )
    server.service = service
    return server
