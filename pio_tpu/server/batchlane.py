"""Cross-worker shared-memory batch lane for the serving pool.

The SO_REUSEPORT pool multiplies host-path QPS, but it FRAGMENTS batch
occupancy: each worker process runs its own micro-batcher over 1/N of
the traffic, so no worker ever collects a batch worth dispatching and
the device sits idle between N small calls. The lane re-aggregates:
non-device workers enqueue their (already admitted + validated) query
bodies into a shared-memory ring and block on an event; the
device-owning worker (``device_worker=True``, idx 0 in
``worker_pool.py``) drains every stripe, serves ALL workers' queries as
ONE bucket-shaped dispatch (see ``bucketcache.py``), and writes each
result back into the slot it came from — batch occupancy scales with
pool size instead of per-process concurrency.

Machinery: one mmapped file of fixed layout (the ``PoolMetricsSegment``
idiom — supervisor creates, workers reopen by path; works under the
``spawn`` context), plus two ``multiprocessing.Event`` doorbells: one
shared request doorbell the drainer sleeps on, one response event per
worker. Any object with ``set/clear/wait`` works, so tests drive the
protocol with ``threading.Event`` in a single process.

Slot protocol (single writer per field — no cross-process locks):

- Each worker owns one STRIPE of slots; only that worker's request
  threads ever write a slot's ``req_seq``/request payload, and only the
  drainer ever writes ``resp_seq``/response payload. Ownership of the
  shared payload region passes with the seq handshake (SPSC style).
- Post:    write payload + lengths, then ``req_seq = s`` (odd).
- Drain:   a slot with odd ``req_seq != resp_seq`` holds a request.
- Respond: write payload + status, then ``resp_seq = s``.
- Free:    the submitter consumes the response and sets
  ``req_seq = s + 1`` (even). A submitter that TIMED OUT leaves the
  slot alone (the drainer may still be writing); the allocator reclaims
  it later, once ``resp_seq`` catches up — a lost wakeup can strand a
  slot for one drain cycle, never corrupt it.

Payloads are UTF-8 JSON (query body in, jsonable result out): the lane
moves REQUESTS, not tensors, so every template — and every query-path
hook on the device worker — works unchanged. Oversized bodies and a
full stripe degrade to the submitter's local predict path (counted via
``pio_tpu_batchlane_full_total``), never to an error.

Request payloads have ONE binary alternative (ISSUE 8): an int8-wire
query packed as ``PACKED_MAGIC + u32 dim + dim int8 codes``. A JSON
body always starts with ``{``/``[``/a quote, so the NUL-led magic can
never collide; the drainer hands a decoded :class:`PackedQuery` to its
dispatch function instead of a JSON body, and the device worker
dequantizes it with the resident scorer's training scales (exact
round trip — see ``server/residency.py``). Responses stay JSON in both
cases: the win is the REQUEST direction, where a feature vector crosses
the ring as one byte per column instead of its decimal text.

Layout (little-endian)::

    0   8s  magic  b"PIOLANE1"
    8   I   n_workers
    12  I   slots_per_worker
    16  I   payload_bytes (per slot)
    20  12x reserved
    32  n_workers stripes of slots_per_worker slots
        slot: 32-byte header (req_seq Q, resp_seq Q, req_len I,
        resp_len I, status I, reserved I) + payload_bytes
"""

from __future__ import annotations

import json
import logging
import mmap
import os
import struct
import threading
from typing import Callable, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.faults import failpoint
from pio_tpu.obs.metrics import monotonic_s

log = logging.getLogger("pio_tpu.batchlane")

MAGIC = b"PIOLANE1"
HEADER_BYTES = 32
SLOT_HEADER_BYTES = 32

#: per-worker ring depth — bounds how many requests one worker can have
#: in flight through the lane (beyond it: local fallback, not an error)
DEFAULT_SLOTS = 64
#: per-slot payload capacity; a top-N query body is ~100 bytes and its
#: response ~1 KiB, so 16 KiB rides out fat black_lists comfortably
DEFAULT_PAYLOAD_BYTES = 16384

#: response status codes (drainer-written)
STATUS_OK = 0
STATUS_ERROR = 1

_SLOT_HDR = struct.Struct("<QQIII4x")  # pio: frame=lane-slot

#: packed int8 request frame: magic + u32 code count + the codes. The
#: leading NUL is the JSON/binary discriminator (see module docstring).
PACKED_MAGIC = b"\x00Q8\x01"
_PACKED_HDR = struct.Struct("<4sI")  # pio: frame=lane-packed


class PackedQuery:
    """An int8-wire query off the lane ring: ``codes`` is a ``[dim]``
    int8 numpy array of quantized features. The drainer's dispatch
    function (the device worker) rebuilds the template Query via the
    resident scorer's ``dequantize`` + ``query_factory``."""

    __slots__ = ("codes",)

    def __init__(self, codes):
        self.codes = codes

    def __len__(self):
        return len(self.codes)


def pack_query_i8(codes) -> bytes:  # pio: hotpath=zerocopy
    """Encode a ``[dim]`` int8 code vector as a lane request frame."""
    import numpy as np

    codes = np.ascontiguousarray(codes, np.int8).reshape(-1)
    # the one serialization copy: device codes -> wire frame
    # pio: disable=hotpath-zero-copy
    return _PACKED_HDR.pack(PACKED_MAGIC, len(codes)) + codes.tobytes()


def unpack_query_i8(payload: bytes) -> PackedQuery:  # pio: hotpath=zerocopy
    """Decode a packed frame (the caller already matched the magic)."""
    import numpy as np

    magic, dim = _PACKED_HDR.unpack_from(payload)
    if magic != PACKED_MAGIC or len(payload) != _PACKED_HDR.size + dim:
        raise ValueError("malformed packed lane frame")
    return PackedQuery(
        np.frombuffer(payload, np.int8, count=dim,
                      offset=_PACKED_HDR.size).copy()
    )


def packed_frame_ok(frame) -> bool:  # pio: hotpath=zerocopy
    """Structural check of a packed frame (any bytes-like) WITHOUT
    decoding it: magic matches and the declared dim accounts for the
    length exactly. The HTTP fast path gates on this before shipping
    body bytes straight into the shm ring — a malformed frame must be a
    client 400, not a drainer-side ValueError burning a lane slot."""
    if len(frame) < _PACKED_HDR.size:
        return False
    magic, dim = _PACKED_HDR.unpack_from(frame)
    return magic == PACKED_MAGIC and len(frame) == _PACKED_HDR.size + dim


class LaneFallback(Exception):
    """Lane unavailable for this request (stripe full, oversize body,
    response timeout, oversize/failed response) — the caller serves the
    query locally. ``reason`` feeds the full/fallback counter label-free
    log line."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class BatchLaneSegment:
    """One mmapped lane file; created by the pool supervisor, reopened
    by every worker."""

    def __init__(self, path: str, n_workers: int, slots_per_worker: int,
                 payload_bytes: int, _file=None, _map=None):
        self.path = path
        self.n_workers = n_workers
        self.slots_per_worker = slots_per_worker
        self.payload_bytes = payload_bytes
        self._f = _file
        self._m = _map

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def create(cls, path: str, n_workers: int,
               slots_per_worker: int = 0,
               payload_bytes: int = 0) -> "BatchLaneSegment":
        slots_per_worker = slots_per_worker or knobs.knob_int(
            "PIO_TPU_LANE_SLOTS"
        )
        payload_bytes = payload_bytes or knobs.knob_int(
            "PIO_TPU_LANE_SLOT_BYTES"
        )
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        slot_bytes = SLOT_HEADER_BYTES + payload_bytes
        size = HEADER_BYTES + n_workers * slots_per_worker * slot_bytes
        with open(path, "wb") as f:
            f.write(MAGIC)
            # pio: frame=lane-header
            f.write(struct.pack(
                "<III", n_workers, slots_per_worker, payload_bytes
            ))
            f.write(b"\0" * (size - 20))
        return cls.open(path)

    @classmethod
    def open(cls, path: str) -> "BatchLaneSegment":
        f = open(path, "r+b")
        try:
            head = f.read(HEADER_BYTES)
            if len(head) < HEADER_BYTES or head[:8] != MAGIC:
                raise ValueError(f"{path}: not a batch lane segment")
            # pio: frame=lane-header
            n_workers, slots, payload = struct.unpack_from("<III", head, 8)
            slot_bytes = SLOT_HEADER_BYTES + payload
            size = HEADER_BYTES + n_workers * slots * slot_bytes
            m = mmap.mmap(f.fileno(), size)
        except BaseException:
            f.close()
            raise
        return cls(path, n_workers, slots, payload, _file=f, _map=m)

    def close(self) -> None:
        if self._m is not None:
            self._m.close()
            self._m = None
        if self._f is not None:
            self._f.close()
            self._f = None

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # -- slot access -------------------------------------------------------
    def _slot_off(self, worker: int, slot: int) -> int:
        if not (0 <= worker < self.n_workers):
            raise IndexError(f"worker {worker} of {self.n_workers}")
        if not (0 <= slot < self.slots_per_worker):
            raise IndexError(f"slot {slot} of {self.slots_per_worker}")
        slot_bytes = SLOT_HEADER_BYTES + self.payload_bytes
        return HEADER_BYTES + (
            worker * self.slots_per_worker + slot
        ) * slot_bytes

    def _hdr(self, worker: int, slot: int) -> Tuple[int, int, int, int, int]:
        """(req_seq, resp_seq, req_len, resp_len, status)."""
        return _SLOT_HDR.unpack_from(self._m, self._slot_off(worker, slot))

    def post_request(self, worker: int, slot: int, payload: bytes) -> int:  # pio: hotpath=zerocopy
        """Submitter side: write the request and publish it by bumping
        ``req_seq`` to odd. Returns the posted seq. The caller must own
        the slot (even ``req_seq`` == ``resp_seq`` state)."""
        off = self._slot_off(worker, slot)
        req_seq, _, _, _, _ = _SLOT_HDR.unpack_from(self._m, off)
        s = req_seq + 1  # even -> odd
        body_off = off + SLOT_HEADER_BYTES
        self._m[body_off:body_off + len(payload)] = payload
        struct.pack_into("<I", self._m, off + 16, len(payload))  # pio: frame=lane-slot
        # seq write LAST: publishing the request is the linearization
        # point the drainer scans for
        struct.pack_into("<Q", self._m, off, s)  # pio: frame=lane-slot
        return s

    def read_request(self, worker: int, slot: int) -> Optional[Tuple[int, bytes]]:  # pio: hotpath=zerocopy
        """Drainer side: (req_seq, payload) when the slot holds an
        unanswered request, else None."""
        off = self._slot_off(worker, slot)
        req_seq, resp_seq, req_len, _, _ = _SLOT_HDR.unpack_from(self._m, off)
        if req_seq % 2 == 0 or resp_seq == req_seq:
            return None
        body_off = off + SLOT_HEADER_BYTES
        # copy-out is deliberate: the mmap slot is reused as soon
        # as the response posts, so the request must not alias it
        # pio: disable=hotpath-zero-copy
        return req_seq, bytes(self._m[body_off:body_off + req_len])

    # pio: hotpath=zerocopy
    def post_response(self, worker: int, slot: int, req_seq: int,
                      payload: bytes, status: int = STATUS_OK) -> None:
        """Drainer side: write the response and publish it by advancing
        ``resp_seq`` to the request's seq."""
        off = self._slot_off(worker, slot)
        body_off = off + SLOT_HEADER_BYTES
        self._m[body_off:body_off + len(payload)] = payload
        struct.pack_into("<II", self._m, off + 20, len(payload), status)  # pio: frame=lane-slot
        struct.pack_into("<Q", self._m, off + 8, req_seq)  # pio: frame=lane-slot

    # pio: hotpath=zerocopy
    def read_response(self, worker: int, slot: int,
                      req_seq: int) -> Optional[Tuple[int, bytes]]:
        """Submitter side: (status, payload) once the drainer answered
        seq ``req_seq``, else None."""
        off = self._slot_off(worker, slot)
        _, resp_seq, _, resp_len, status = _SLOT_HDR.unpack_from(self._m, off)
        if resp_seq != req_seq:
            return None
        body_off = off + SLOT_HEADER_BYTES
        # copy-out is deliberate: release() frees the slot for the
        # next request before the caller finishes with the payload
        # pio: disable=hotpath-zero-copy
        return status, bytes(self._m[body_off:body_off + resp_len])

    def release(self, worker: int, slot: int, req_seq: int) -> None:
        """Submitter side: response consumed; free the slot (odd seq →
        even)."""
        # pio: frame=lane-slot
        struct.pack_into(
            "<Q", self._m, self._slot_off(worker, slot), req_seq + 1
        )

    def reclaimable(self, worker: int, slot: int) -> bool:
        """True when the slot is idle from the drainer's point of view:
        even seq (free) or answered-but-unreleased (abandoned by a
        timed-out submitter — safe to recycle, the drainer is done with
        it)."""
        req_seq, resp_seq, _, _, _ = self._hdr(worker, slot)
        return req_seq % 2 == 0 or resp_seq == req_seq

    def pending_depth(self) -> int:
        """Unanswered requests across all stripes (depth gauge)."""
        n = 0
        for w in range(self.n_workers):
            for s in range(self.slots_per_worker):
                req_seq, resp_seq, _, _, _ = self._hdr(w, s)
                if req_seq % 2 == 1 and resp_seq != req_seq:
                    n += 1
        return n


class LaneClient:
    """Non-device worker's submit side: one instance per worker process,
    shared by its request threads (slot allocation is locked; the wait
    is per-thread)."""

    def __init__(self, seg: BatchLaneSegment, worker_idx: int,
                 doorbell, resp_event, timeout_s: float = 0.0):
        self._seg = seg
        self._idx = worker_idx
        self._doorbell = doorbell
        self._resp_event = resp_event
        self._timeout_s = timeout_s or knobs.knob_float(
            "PIO_TPU_LANE_TIMEOUT_S"
        )
        self._alloc_lock = threading.Lock()
        #: slots this process believes are in flight (its own stripe —
        #: this worker is the only submitter writing it, so local
        #: bookkeeping is authoritative; zombies re-validate via seqs)
        self._busy: set = set()

    @property
    def timeout_s(self) -> float:
        """Default wait for a response (deadline-aware callers clamp)."""
        return self._timeout_s

    def _acquire_slot(self) -> Optional[int]:
        with self._alloc_lock:
            for s in range(self._seg.slots_per_worker):
                req_seq, resp_seq, _, _, _ = self._seg._hdr(self._idx, s)
                if s in self._busy:
                    # busy = acquired by a thread of THIS process. Steal
                    # only an answered zombie (its submitter timed out
                    # and will never touch the slot again); an even slot
                    # here is mid-post by another thread — hands off.
                    if req_seq % 2 == 1 and resp_seq == req_seq:
                        self._seg.release(self._idx, s, req_seq)
                    else:
                        continue
                elif req_seq % 2 == 1:
                    # stale in-flight from a previous process life: safe
                    # to recycle once the drainer answered, else skip
                    if resp_seq == req_seq:
                        self._seg.release(self._idx, s, req_seq)
                    else:
                        continue
                self._busy.add(s)
                return s
        return None

    # pio: hotpath=zerocopy
    def _submit_payload(self, payload,
                        timeout_s: Optional[float] = None):
        """Ship one pre-encoded request payload (bytes or memoryview)
        through the ring and park until the drainer answers or the
        timeout elapses; returns ``(status, response_bytes)``. The
        payload is written straight into the shm slot by post_request —
        this function never copies or re-encodes it. Raises
        :class:`LaneFallback` whenever the lane cannot answer."""
        failpoint("batchlane.submit")
        if len(payload) > self._seg.payload_bytes:
            raise LaneFallback("oversize")
        slot = self._acquire_slot()
        if slot is None:
            raise LaneFallback("full")
        seq = self._seg.post_request(self._idx, slot, payload)
        self._doorbell.set()
        deadline = monotonic_s() + (timeout_s or self._timeout_s)
        while True:
            got = self._seg.read_response(self._idx, slot, seq)
            if got is not None:
                break
            if monotonic_s() >= deadline:
                # leave the slot in flight; _acquire_slot reclaims it
                # once the drainer responds (slot stays busy until then)
                raise LaneFallback("timeout")
            # clear-then-check-then-wait: the event may have been set for
            # an earlier response; the slot header is the ground truth
            self._resp_event.clear()
            got = self._seg.read_response(self._idx, slot, seq)
            if got is not None:
                break
            # bounded 2 ms doze between slot-header polls; submit
            # is synchronous RPC, the caller expects to park here
            # pio: disable=hotpath-blocking
            self._resp_event.wait(0.002)
        status, resp = got
        self._seg.release(self._idx, slot, seq)
        with self._alloc_lock:
            self._busy.discard(slot)
        return status, resp

    # pio: hotpath=zerocopy
    def submit(self, body: dict, timeout_s: Optional[float] = None,
               packed: Optional[bytes] = None):
        """Serve one query body through the device worker; blocks until
        the response lands or the timeout elapses. Raises
        :class:`LaneFallback` whenever the lane cannot answer — the
        caller's local predict path is the degradation, so the lane can
        never make a request fail that would have succeeded without it.

        ``packed`` ships a pre-encoded binary frame (``pack_query_i8``)
        instead of JSON-encoding ``body`` — the int8 wire's request
        direction. Callers that also want the RESPONSE undecoded use
        :meth:`submit_packed` instead."""
        if packed is not None:
            payload = packed
        else:
            try:
                # legacy JSON envelope for un-packed callers; the
                # packed int8 branch above is the zero-copy wire
                # (ROADMAP item 1 retires this encode)
                # pio: disable=hotpath-zero-copy
                payload = json.dumps(body).encode("utf-8")
            except (TypeError, ValueError):
                raise LaneFallback("unserializable")
        status, resp = self._submit_payload(payload, timeout_s)
        if status != STATUS_OK:
            raise LaneFallback("remote_error")
        try:
            # legacy JSON envelope decode, mirror of the encode
            # above (packed responses bypass submit entirely)
            # pio: disable=hotpath-zero-copy
            return json.loads(resp.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            raise LaneFallback("undecodable_response")

    # pio: hotpath=zerocopy
    def submit_packed(self, packed,
                      timeout_s: Optional[float] = None) -> bytes:
        """Raw-frame submit for the zero-copy HTTP ingest: ``packed``
        is an already-wire-shaped frame (bytes or a memoryview into the
        front's connection buffer) and the return value is the
        drainer's response payload UNDECODED — already JSON bytes that
        the front hands straight to the response writer. Socket → shm
        ring → socket with no codec and no intermediate copies on this
        side of the lane."""
        status, resp = self._submit_payload(packed, timeout_s)
        if status != STATUS_OK:
            raise LaneFallback("remote_error")
        return resp


class LaneDrainer:
    """Device worker's drain loop: sleeps on the doorbell, gathers every
    stripe's pending requests, serves them through ``dispatch_fn`` (one
    bucket-shaped batch), and answers each slot.

    ``dispatch_fn(bodies) -> results`` returns one jsonable result per
    body; raising fails the WHOLE drain cycle's requests to their local
    fallbacks (status=error), mirroring the micro-batcher's poisoned-
    batch semantics.
    """

    def __init__(self, seg: BatchLaneSegment,
                 dispatch_fn: Callable[[List[dict]], List],
                 doorbell, resp_events, poll_s: float = 0.05,
                 on_drain: Optional[Callable[[int, int], None]] = None):
        self._seg = seg
        self._dispatch = dispatch_fn
        self._doorbell = doorbell
        self._resp_events = resp_events
        self._poll_s = poll_s
        #: on_drain(n_requests, n_batches) after each served cycle —
        #: metric accounting hook (drained/batches counters, depth gauge)
        self._on_drain = on_drain
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.cycles = 0
        self.drained = 0

    def start(self) -> "LaneDrainer":
        self._thread = threading.Thread(
            target=self._run, name="pio-tpu-batchlane", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        self._doorbell.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    @property
    def thread(self) -> Optional[threading.Thread]:
        return self._thread

    def _collect(self) -> List[Tuple[int, int, int, dict]]:
        """(worker, slot, req_seq, body) for every pending request.
        Undecodable bodies are answered with an error immediately."""
        out = []
        for w in range(self._seg.n_workers):
            for s in range(self._seg.slots_per_worker):
                got = self._seg.read_request(w, s)
                if got is None:
                    continue
                seq, payload = got
                try:
                    if payload[:4] == PACKED_MAGIC:
                        body = unpack_query_i8(payload)
                    else:
                        body = json.loads(payload.decode("utf-8"))
                except (UnicodeDecodeError, ValueError):
                    self._seg.post_response(
                        w, s, seq, b'"undecodable"', STATUS_ERROR
                    )
                    continue
                out.append((w, s, seq, body))
        return out

    def drain_once(self) -> int:
        """One collect→dispatch→respond cycle; returns requests served.
        Public so tests (and a pool-less embedding) can drive the lane
        without the thread."""
        failpoint("batchlane.drain")
        pending = self._collect()
        if not pending:
            return 0
        bodies = [p[3] for p in pending]
        try:
            results = self._dispatch(bodies)
            if len(results) != len(bodies):
                raise ValueError(
                    f"dispatch returned {len(results)} results "
                    f"for {len(bodies)} bodies"
                )
            payloads = [
                (json.dumps(r).encode("utf-8"), STATUS_OK) for r in results
            ]
        except Exception:
            log.exception(
                "lane dispatch failed; members fall back to local predict"
            )
            payloads = [(b'"dispatch failed"', STATUS_ERROR)] * len(bodies)
        woken = set()
        for (w, s, seq, _), (payload, status) in zip(pending, payloads):
            if len(payload) > self._seg.payload_bytes:
                payload, status = b'"oversize response"', STATUS_ERROR
            self._seg.post_response(w, s, seq, payload, status)
            woken.add(w)
        for w in woken:
            self._resp_events[w].set()
        self.cycles += 1
        self.drained += len(pending)
        if self._on_drain is not None:
            self._on_drain(len(pending), 1)
        return len(pending)

    def _run(self) -> None:  # pio: hotpath
        while not self._stopped:
            # the drain loop parks on the doorbell by design; the
            # wait bounds idle latency, not request latency
            # pio: disable=hotpath-blocking
            self._doorbell.wait(self._poll_s)
            self._doorbell.clear()
            if self._stopped:
                return
            try:
                while self.drain_once():
                    pass  # drain to empty before sleeping again
            except Exception:
                log.exception("lane drain cycle failed")
