"""Webhook connectors — third-party payloads → Events.

Rebuild of the reference's ``data/.../data/api/webhooks/`` +
``data/webhooks/{segmentio,mailchimp}`` (UNVERIFIED paths; see SURVEY.md):
a connector turns a JSON or form payload into the Event wire format. The
Event Server exposes ``POST /webhooks/<name>.json`` (JSON connectors) and
``POST /webhooks/<name>.form`` (form connectors).
"""

from __future__ import annotations

import abc
from typing import Any, Dict

from urllib.parse import parse_qs


class ConnectorError(ValueError):
    pass


class JsonConnector(abc.ABC):
    """JSON payload → Event wire dict (reference ``JsonConnector``)."""

    @abc.abstractmethod
    def to_event_dict(self, payload: Dict[str, Any]) -> Dict[str, Any]: ...


class FormConnector(abc.ABC):
    """Form payload → Event wire dict (reference ``FormConnector``)."""

    @abc.abstractmethod
    def to_event_dict(self, form: Dict[str, str]) -> Dict[str, Any]: ...


class SegmentIOConnector(JsonConnector):
    """segment.com track/identify/page/screen payloads
    (reference ``SegmentIOConnector``)."""

    SUPPORTED = {"track", "identify", "page", "screen", "group", "alias"}

    def to_event_dict(self, payload):
        typ = payload.get("type")
        if typ not in self.SUPPORTED:
            raise ConnectorError(f"unsupported segment.io type {typ!r}")
        user = payload.get("userId") or payload.get("anonymousId")
        if not user:
            raise ConnectorError("payload needs userId or anonymousId")
        out = {
            "event": (
                payload.get("event") if typ == "track" and payload.get("event")
                else typ
            ),
            "entityType": "user",
            "entityId": str(user),
            "properties": payload.get("properties")
            or payload.get("traits")
            or {},
        }
        if payload.get("timestamp"):
            out["eventTime"] = payload["timestamp"]
        return out


class MailChimpConnector(FormConnector):
    """MailChimp webhook form posts (reference ``MailChimpConnector``)."""

    SUPPORTED = {"subscribe", "unsubscribe", "profile", "upemail", "cleaned",
                 "campaign"}

    def to_event_dict(self, form):
        typ = form.get("type")
        if typ not in self.SUPPORTED:
            raise ConnectorError(f"unsupported mailchimp type {typ!r}")
        email = form.get("data[email]") or form.get("data[new_email]")
        if not email:
            raise ConnectorError("mailchimp payload needs data[email]")
        props = {
            k[len("data["):-1]: v
            for k, v in form.items()
            if k.startswith("data[") and k.endswith("]")
        }
        out = {
            "event": typ,
            "entityType": "user",
            "entityId": email,
            "properties": props,
        }
        if form.get("fired_at"):
            out["eventTime"] = form["fired_at"].replace(" ", "T") + "Z"
        return out


class ExampleJsonConnector(JsonConnector):
    """Identity-ish connector used by tests (reference
    ``webhooks/exampleJson``)."""

    def to_event_dict(self, payload):
        if "event" not in payload:
            raise ConnectorError("payload needs 'event'")
        return payload


def parse_form(raw: str) -> Dict[str, str]:
    return {k: v[0] for k, v in parse_qs(raw, keep_blank_values=True).items()}


#: name → connector registry (reference wires connectors statically too)
JSON_CONNECTORS: Dict[str, JsonConnector] = {
    "segmentio": SegmentIOConnector(),
    "example": ExampleJsonConnector(),
}
FORM_CONNECTORS: Dict[str, FormConnector] = {
    "mailchimp": MailChimpConnector(),
}
