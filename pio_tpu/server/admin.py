"""Admin API server — REST app/key management.

Rebuild of the reference's experimental ``tools/.../tools/admin/``
(AdminAPI.scala, AdminServiceActor, CommandClient — UNVERIFIED paths;
SURVEY.md §2.4). Routes:

- ``GET /``                      — server alive info;
- ``GET /cmd/status``            — storage backend self-check
  (≙ ``Storage.verifyAllDataObjects`` behind ``pio status``);
- ``GET /cmd/app``               — list apps with their access keys;
- ``POST /cmd/app``              — create app ``{"name": ...}`` (+ access key);
- ``DELETE /cmd/app/<name>``     — delete app, its keys, channels, events;
- ``DELETE /cmd/app/<name>/data``— delete the app's event data only.
"""

from __future__ import annotations

from typing import Any, Tuple

from pio_tpu.server.http import (
    HTTPError, JsonHTTPServer, Request, Router, keys_equal,
)
from pio_tpu.storage import AccessKey, App, Storage


class AdminService:
    """≙ reference ``AdminServiceActor`` + ``CommandClient``.

    Mutating routes follow the query server's admin-guard convention:
    loopback-only unless an ``admin_key`` is configured and presented.
    """

    def __init__(self, admin_key=None):
        self.admin_key = admin_key
        self.router = Router()
        self.router.add("GET", "/", self.index)
        self.router.add("GET", "/cmd/status", self.status)
        self.router.add("GET", "/cmd/app", self.list_apps)
        self.router.add("POST", "/cmd/app", self.new_app)
        self.router.add("DELETE", "/cmd/app/([^/]+)", self.delete_app)
        self.router.add(
            "DELETE", "/cmd/app/([^/]+)/data", self.delete_app_data
        )

    def _check_admin(self, req: Request):
        if self.admin_key is not None:
            if not keys_equal(req.bearer_key(), self.admin_key):
                raise HTTPError(401, "invalid admin accessKey")
        elif req.client_addr not in ("127.0.0.1", "::1"):
            raise HTTPError(
                403, "mutating admin routes are loopback-only without an "
                     "admin key"
            )

    def index(self, req: Request) -> Tuple[int, Any]:
        return 200, {
            "status": "alive",
            "description": "pio-tpu Admin API",
        }

    def status(self, req: Request) -> Tuple[int, Any]:
        try:
            Storage.verify_all_data_objects()
        except Exception as e:  # surface, don't 500 — it's a health check
            return 200, {"status": "error", "message": str(e)}
        return 200, {"status": "ok"}

    def _app_dict(self, app: App) -> dict:
        keys = Storage.get_meta_data_access_keys().get_by_app_id(app.id)
        return {
            "name": app.name,
            "id": app.id,
            "accessKeys": [k.key for k in keys],
        }

    def list_apps(self, req: Request) -> Tuple[int, Any]:
        apps = Storage.get_meta_data_apps().get_all()
        return 200, {"apps": [self._app_dict(a) for a in apps]}

    def new_app(self, req: Request) -> Tuple[int, Any]:
        self._check_admin(req)
        if not isinstance(req.body, dict) or not req.body.get("name"):
            return 400, {"message": "body must be {\"name\": ...}"}
        name = str(req.body["name"])
        try:
            requested_id = int(req.body.get("id") or 0)
        except (TypeError, ValueError):
            return 400, {"message": "\"id\" must be an integer"}
        apps = Storage.get_meta_data_apps()
        if apps.get_by_name(name) is not None:
            return 409, {"message": f"app {name!r} already exists"}
        app_id = apps.insert(App(requested_id, name))
        key = AccessKey(key="", app_id=app_id, events=())
        key_str = Storage.get_meta_data_access_keys().insert(key)
        return 201, {"name": name, "id": app_id, "accessKeys": [key_str]}

    def _resolve(self, name: str):
        return Storage.get_meta_data_apps().get_by_name(name)

    def delete_app(self, req: Request) -> Tuple[int, Any]:
        self._check_admin(req)
        app = self._resolve(req.path_args[0])
        if app is None:
            return 404, {"message": "app not found"}
        keys = Storage.get_meta_data_access_keys()
        for k in keys.get_by_app_id(app.id):
            keys.delete(k.key)
        chans = Storage.get_meta_data_channels()
        for c in chans.get_by_app_id(app.id):
            Storage.get_levents().remove(app.id, channel_id=c.id)
            chans.delete(c.id)
        Storage.get_levents().remove(app.id)
        Storage.get_meta_data_apps().delete(app.id)
        return 200, {"message": f"deleted app {app.name!r}"}

    def delete_app_data(self, req: Request) -> Tuple[int, Any]:
        self._check_admin(req)
        app = self._resolve(req.path_args[0])
        if app is None:
            return 404, {"message": "app not found"}
        for c in Storage.get_meta_data_channels().get_by_app_id(app.id):
            Storage.get_levents().remove(app.id, channel_id=c.id)
        Storage.get_levents().remove(app.id)
        return 200, {"message": f"deleted data of app {app.name!r}"}


def create_admin_server(
    host: str = "0.0.0.0", port: int = 7071, admin_key=None
) -> JsonHTTPServer:
    """Build (unstarted) admin server — reference ``AdminAPI.main``."""
    service = AdminService(admin_key=admin_key)
    return JsonHTTPServer(service.router, host, port, name="pio-tpu-admin")
