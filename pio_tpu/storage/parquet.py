"""Parquet columnar event store — bulk/training-side backend.

Plays the role of the reference's HBase event store
(``storage/hbase/.../HBPEvents.scala`` — UNVERIFIED path; see SURVEY.md) for
the TPU build: an append-only directory of Parquet shards per (app, channel).
Training reads scan shards with pyarrow predicate pushdown and materialize
columnar :class:`EventFrame`s directly — no per-row Python objects on the hot
path — which then become host-sharded device arrays.

Layout: ``<root>/app_<id>/channel_<cid>/part-<uuid>.parquet``.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import uuid
from typing import Iterable, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.dataset as pa_ds
import pyarrow.parquet as pq

from pio_tpu.data.datamap import DataMap
from pio_tpu.data.event import Event
from pio_tpu.storage import base
from pio_tpu.storage.frame import EventFrame

_SCHEMA = pa.schema(
    [
        ("id", pa.string()),
        ("event", pa.string()),
        ("entity_type", pa.string()),
        ("entity_id", pa.string()),
        ("target_entity_type", pa.string()),
        ("target_entity_id", pa.string()),
        ("properties", pa.string()),  # JSON
        ("event_time_us", pa.int64()),
        ("tags", pa.string()),  # JSON list
        ("pr_id", pa.string()),
        ("creation_time_us", pa.int64()),
    ]
)


from pio_tpu.utils.timeutil import from_micros as _from_us, to_micros as _to_us


class ParquetPEvents(base.PEvents):
    """Append-only Parquet shard store implementing the bulk PEvents SPI."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _dir(self, app_id: int, channel_id) -> str:
        cid = 0 if channel_id is None else int(channel_id)
        return os.path.join(self.root, f"app_{app_id}", f"channel_{cid}")

    # -- write --------------------------------------------------------------
    def write(self, events: Iterable[Event], app_id, channel_id=None) -> None:
        evs = list(events)
        if not evs:
            return
        d = self._dir(app_id, channel_id)
        os.makedirs(d, exist_ok=True)
        table = pa.table(
            {
                "id": [e.event_id or Event.new_event_id() for e in evs],
                "event": [e.event for e in evs],
                "entity_type": [e.entity_type for e in evs],
                "entity_id": [e.entity_id for e in evs],
                "target_entity_type": [e.target_entity_type or "" for e in evs],
                "target_entity_id": [e.target_entity_id or "" for e in evs],
                "properties": [json.dumps(e.properties.to_dict()) for e in evs],
                "event_time_us": [_to_us(e.event_time) for e in evs],
                "tags": [json.dumps(list(e.tags)) for e in evs],
                "pr_id": [e.pr_id or "" for e in evs],
                "creation_time_us": [_to_us(e.creation_time) for e in evs],
            },
            schema=_SCHEMA,
        )
        pq.write_table(table, os.path.join(d, f"part-{uuid.uuid4().hex}.parquet"))

    # -- read ---------------------------------------------------------------
    def _filter_expr(
        self,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=None,
        target_entity_id=None,
    ):
        expr = None

        def conj(e):
            nonlocal expr
            expr = e if expr is None else expr & e

        if start_time is not None:
            conj(pc.field("event_time_us") >= _to_us(start_time))
        if until_time is not None:
            conj(pc.field("event_time_us") < _to_us(until_time))
        if entity_type is not None:
            conj(pc.field("entity_type") == entity_type)
        if entity_id is not None:
            conj(pc.field("entity_id") == entity_id)
        if event_names is not None:
            conj(pc.field("event").isin(list(event_names)))
        if target_entity_type is not None:
            conj(pc.field("target_entity_type") == target_entity_type)
        if target_entity_id is not None:
            conj(pc.field("target_entity_id") == target_entity_id)
        return expr

    def _scan(self, app_id, channel_id, **filters) -> Optional[pa.Table]:
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d) or not os.listdir(d):
            return None
        ds = pa_ds.dataset(d, format="parquet", schema=_SCHEMA)
        return ds.to_table(filter=self._filter_expr(**filters))

    def find(self, app_id, channel_id=None, **filters) -> List[Event]:
        table = self._scan(app_id, channel_id, **filters)
        if table is None:
            return []
        table = table.sort_by("event_time_us")
        cols = {name: table.column(name).to_pylist() for name in table.schema.names}
        out = []
        for i in range(table.num_rows):
            out.append(
                Event(
                    event=cols["event"][i],
                    entity_type=cols["entity_type"][i],
                    entity_id=cols["entity_id"][i],
                    target_entity_type=cols["target_entity_type"][i] or None,
                    target_entity_id=cols["target_entity_id"][i] or None,
                    properties=DataMap._wrap(json.loads(cols["properties"][i])),
                    event_time=_from_us(cols["event_time_us"][i]),
                    tags=tuple(json.loads(cols["tags"][i])),
                    pr_id=cols["pr_id"][i] or None,
                    event_id=cols["id"][i],
                    creation_time=_from_us(cols["creation_time_us"][i]),
                )
            )
        return out

    def find_frame(self, app_id, channel_id=None, **filters) -> EventFrame:
        """Columnar read that never builds per-row Event objects."""
        table = self._scan(app_id, channel_id, **filters)
        if table is None:
            return EventFrame.from_events([])
        table = table.sort_by("event_time_us")
        return EventFrame(
            event=np.asarray(table.column("event").to_pylist(), dtype=object),
            entity_type=np.asarray(
                table.column("entity_type").to_pylist(), dtype=object
            ),
            entity_id=np.asarray(table.column("entity_id").to_pylist(), dtype=object),
            target_entity_type=np.asarray(
                table.column("target_entity_type").to_pylist(), dtype=object
            ),
            target_entity_id=np.asarray(
                table.column("target_entity_id").to_pylist(), dtype=object
            ),
            properties=[json.loads(p) for p in table.column("properties").to_pylist()],
            event_time_us=table.column("event_time_us").to_numpy(),
        )

    def delete(self, event_ids, app_id, channel_id=None) -> None:
        """Bulk delete = rewrite shards without the given ids (compaction)."""
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):
            return
        drop = set(event_ids)
        ds = pa_ds.dataset(d, format="parquet", schema=_SCHEMA)
        table = ds.to_table()
        keep = table.filter(~pc.field("id").isin(list(drop)))
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))
        if keep.num_rows:
            pq.write_table(keep, os.path.join(d, f"part-{uuid.uuid4().hex}.parquet"))

    def compact(self, app_id, channel_id=None) -> None:
        """Merge shards into one file (the HBase-compaction analog)."""
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d) or len(os.listdir(d)) <= 1:
            return
        table = pa_ds.dataset(d, format="parquet", schema=_SCHEMA).to_table()
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))
        pq.write_table(table, os.path.join(d, f"part-{uuid.uuid4().hex}.parquet"))
