"""In-memory storage backend — tests + ephemeral servers.

Plays the role of the reference's H2/in-process JDBC test backends
(SURVEY.md §4: "one spec, many backends"). Implements every SPI trait.
Thread-safe via a single coarse lock (the Event Server inserts from multiple
request threads).
"""

from __future__ import annotations

import datetime as _dt
import threading
import uuid
from dataclasses import replace as _replace
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from pio_tpu.data.event import Event
from pio_tpu.storage import base
from pio_tpu.storage.records import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)


def _match(
    e: Event,
    start_time=None,
    until_time=None,
    entity_type=None,
    entity_id=None,
    event_names=None,
    target_entity_type=None,
    target_entity_id=None,
) -> bool:
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in set(event_names):
        return False
    if target_entity_type is not None and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not None and e.target_entity_id != target_entity_id:
        return False
    return True


class MemLEvents(base.LEvents, base.PEvents):
    """Both LEvents and PEvents over one dict-of-lists store."""

    def __init__(self):
        self._lock = threading.RLock()
        # (app_id, channel_id) -> {event_id: Event}
        self._events: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}

    def _bucket(self, app_id: int, channel_id) -> Dict[str, Event]:
        return self._events.setdefault((app_id, channel_id), {})

    # -- LEvents ------------------------------------------------------------
    def init_channel(self, app_id, channel_id=None) -> bool:
        with self._lock:
            self._bucket(app_id, channel_id)
        return True

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        with self._lock:
            eid = event.event_id or Event.new_event_id()
            self._bucket(app_id, channel_id)[eid] = event.with_event_id(eid)
            return eid

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        with self._lock:
            return self._bucket(app_id, channel_id).get(event_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        with self._lock:
            return self._bucket(app_id, channel_id).pop(event_id, None) is not None

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=None,
        target_entity_id=None,
        limit=None,
        reversed_order=False,
    ) -> List[Event]:
        with self._lock:
            evs = list(self._bucket(app_id, channel_id).values())
        evs = [
            e
            for e in evs
            if _match(
                e,
                start_time,
                until_time,
                entity_type,
                entity_id,
                event_names,
                target_entity_type,
                target_entity_id,
            )
        ]
        evs.sort(key=lambda e: e.event_time, reverse=reversed_order)
        if limit is not None and limit >= 0:
            evs = evs[:limit]
        return evs

    def remove(self, app_id, channel_id=None) -> bool:
        with self._lock:
            self._events.pop((app_id, channel_id), None)
        return True

    # -- PEvents ------------------------------------------------------------
    def write(self, events: Iterable[Event], app_id, channel_id=None) -> None:
        with self._lock:
            for e in events:
                self.insert(e, app_id, channel_id)

    # PEvents.find shares the LEvents signature minus limit; the LEvents
    # implementation above already covers it.

    def delete_bulk(self, event_ids, app_id, channel_id=None) -> None:
        with self._lock:
            for eid in event_ids:
                self._bucket(app_id, channel_id).pop(eid, None)


# Shared facade mapping the bulk PEvents SPI onto the combined store.
MemPEvents = base.PEventsAdapter


class MemApps(base.Apps):
    def __init__(self):
        self._lock = threading.RLock()
        self._apps: Dict[int, App] = {}
        self._next = 1

    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            if self.get_by_name(app.name) is not None:
                return None
            app_id = app.id
            if app_id == 0:
                app_id = self._next
            if app_id in self._apps:
                return None
            self._next = max(self._next, app_id) + 1
            self._apps[app_id] = App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> Optional[App]:
        for a in self._apps.values():
            if a.name == name:
                return a
        return None

    def get_all(self) -> List[App]:
        return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app: App) -> bool:
        with self._lock:
            if app.id not in self._apps:
                return False
            self._apps[app.id] = app
            return True

    def delete(self, app_id: int) -> bool:
        with self._lock:
            return self._apps.pop(app_id, None) is not None


class MemAccessKeys(base.AccessKeys):
    def __init__(self):
        self._lock = threading.RLock()
        self._keys: Dict[str, AccessKey] = {}

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self._lock:
            ak = access_key
            if not ak.key:
                ak = AccessKey.generate(ak.app_id, ak.events)
            if ak.key in self._keys:
                return None
            self._keys[ak.key] = ak
            return ak.key

    def get(self, key: str) -> Optional[AccessKey]:
        return self._keys.get(key)

    def get_all(self) -> List[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._keys.values() if k.app_id == app_id]

    def update(self, access_key: AccessKey) -> bool:
        with self._lock:
            if access_key.key not in self._keys:
                return False
            self._keys[access_key.key] = access_key
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._keys.pop(key, None) is not None


class MemChannels(base.Channels):
    def __init__(self):
        self._lock = threading.RLock()
        self._channels: Dict[int, Channel] = {}
        self._next = 1

    def insert(self, channel: Channel) -> Optional[int]:
        with self._lock:
            if not Channel.is_valid_name(channel.name):
                return None
            cid = channel.id or self._next
            if cid in self._channels:
                return None
            self._next = max(self._next, cid) + 1
            self._channels[cid] = Channel(cid, channel.name, channel.app_id)
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._channels.values() if c.app_id == app_id]

    def delete(self, channel_id: int) -> bool:
        with self._lock:
            return self._channels.pop(channel_id, None) is not None


class MemEngineInstances(base.EngineInstances):
    def __init__(self):
        self._lock = threading.RLock()
        self._instances: Dict[str, EngineInstance] = {}

    def insert(self, instance: EngineInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            self._instances[iid] = (
                instance if instance.id else _replace(instance, id=iid)
            )
            return iid

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return self._instances.get(instance_id)

    def get_all(self) -> List[EngineInstance]:
        return list(self._instances.values())

    def get_completed(self, engine_id, engine_version, engine_variant):
        out = [
            i
            for i in self._instances.values()
            if i.status == "COMPLETED"
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        out.sort(key=lambda i: i.start_time, reverse=True)
        return out

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        done = self.get_completed(engine_id, engine_version, engine_variant)
        return done[0] if done else None

    def update(self, instance: EngineInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = instance
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemEvaluationInstances(base.EvaluationInstances):
    def __init__(self):
        self._lock = threading.RLock()
        self._instances: Dict[str, EvaluationInstance] = {}

    def insert(self, instance: EvaluationInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            self._instances[iid] = (
                instance if instance.id else _replace(instance, id=iid)
            )
            return iid

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return self._instances.get(instance_id)

    def get_all(self) -> List[EvaluationInstance]:
        return list(self._instances.values())

    def get_completed(self) -> List[EvaluationInstance]:
        out = [i for i in self._instances.values() if i.status == "COMPLETED"]
        out.sort(key=lambda i: i.start_time, reverse=True)
        return out

    def update(self, instance: EvaluationInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = instance
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemModels(base.Models):
    def __init__(self):
        self._models: Dict[str, Model] = {}

    def insert(self, model: Model) -> None:
        self._models[model.id] = model

    def get(self, model_id: str) -> Optional[Model]:
        return self._models.get(model_id)

    def delete(self, model_id: str) -> bool:
        return self._models.pop(model_id, None) is not None
