"""Content-addressed blob Models store — the HDFS/S3 slot.

The reference ships two remote model stores (``storage/hdfs/.../
HDFSModels.scala``, ``storage/s3/.../S3Models.scala`` — UNVERIFIED paths;
SURVEY.md §2.3) that write one opaque file per engine-instance id into a
cluster filesystem. This rebuild generalizes the slot instead of binding
to one vendor client:

- A tiny **BlobBackend SPI** (put/get/delete/exists on flat keys) keyed by
  URI scheme. ``file://`` AND a real network scheme ship in-tree:
  ``http(s)://`` talks to the blob daemon
  (:mod:`pio_tpu.server.blob_server`, ``python -m pio_tpu blobserver``),
  so model bytes genuinely cross a socket — the remoteness that defines
  the HDFS/S3 rows. ``gs://``/``s3://``/``hdfs://`` plug in by
  registering a backend for their scheme (:func:`register_blob_scheme`)
  — the Models trait above them does not change.
- **Content addressing**: blobs live at ``objects/<aa>/<sha256>`` and a
  mutable ``refs/<model-id>`` pointer names the current blob. Identical
  models dedupe, every read is digest-verified end-to-end (a corrupt or
  torn remote object is an error, not a silently wrong model), and a
  model artifact can be mirrored between stores by copying immutable
  objects without rewriting metadata.

Select it with::

    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=BLOB
    PIO_STORAGE_SOURCES_BLOB_TYPE=blob
    PIO_STORAGE_SOURCES_BLOB_PATH=file:///var/pio/models   # or gs://bucket/prefix
"""

from __future__ import annotations

import abc
import hashlib
import io
import os
import tempfile
from typing import Callable, Dict, List, Optional
from urllib.parse import quote, urlparse

from pio_tpu.utils import knobs
from pio_tpu.faults import failpoint
from pio_tpu.storage import base
from pio_tpu.storage.durability import fsync_fileobj, replace_durable
from pio_tpu.storage.records import Model

#: reserved suffix for in-flight atomic-write staging files; list() hides
#: exactly this suffix, so ordinary keys (even ones ending ".tmp") are
#: never masked. Don't name blobs with it.
_STAGING_SUFFIX = ".pio-staging"


class BlobBackend(abc.ABC):
    """Flat key → bytes store (the part a gs://, s3://, or hdfs:// client
    must implement; keys use '/' separators and are safe path segments)."""

    @abc.abstractmethod
    def put(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...

    @abc.abstractmethod
    def exists(self, key: str) -> bool: ...

    @abc.abstractmethod
    def list(self, prefix: str) -> List[str]:
        """Keys under a prefix (used by ref-count garbage collection)."""


class FileBlobBackend(BlobBackend):
    """file:// — atomic single-file objects under a root directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.normpath(os.path.join(self.root, key))
        if not p.startswith(os.path.normpath(self.root) + os.sep):
            raise base.StorageError(f"blob key escapes the root: {key!r}")
        return p

    def put(self, key: str, data: bytes) -> None:
        self.put_file(key, io.BytesIO(data))

    def put_file(self, key: str, src, chunk_size: int = 1 << 20) -> int:
        """Stream an open binary file into the store in constant memory
        (the blob daemon's PUT path). Returns the byte count stored.

        The temp file is uniquely named per call (mkstemp) — the daemon
        is threaded, and two concurrent PUTs to one key must each write
        their own staging file; last os.replace wins atomically."""
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=os.path.basename(p) + ".", suffix=_STAGING_SUFFIX,
            dir=os.path.dirname(p),
        )
        n = 0
        try:
            with os.fdopen(fd, "wb") as f:
                while chunk := src.read(chunk_size):
                    f.write(chunk)
                    n += len(chunk)
                # durable rename (durability knob): bytes on disk before
                # the rename publishes them, dir entry fsynced after
                fsync_fileobj(f)
            failpoint("storage.blobstore.persist")
            replace_durable(tmp, p)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return n

    def get(self, key: str) -> Optional[bytes]:
        p = self._path(key)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def delete(self, key: str) -> bool:
        p = self._path(key)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def local_path(self, key: str) -> Optional[str]:
        """Filesystem path of a stored blob (None if absent) — lets the
        blob daemon stream GETs instead of buffering whole artifacts."""
        p = self._path(key)
        return p if os.path.exists(p) else None

    def list(self, prefix: str) -> List[str]:
        base_dir = self._path(prefix) if prefix else self.root
        out = []
        for dirpath, _dirs, files in os.walk(base_dir):
            for f in files:
                if f.endswith(_STAGING_SUFFIX):
                    continue  # in-flight put_file staging, not a blob
                full = os.path.join(dirpath, f)
                out.append(os.path.relpath(full, self.root).replace(
                    os.sep, "/"
                ))
        return out


class HTTPBlobBackend(BlobBackend):
    """``http(s)://`` — client of the blob daemon
    (:mod:`pio_tpu.server.blob_server`), i.e. the in-tree REMOTE Models
    backend: model bytes cross a socket, nothing is shared with the
    server but the URL. stdlib urllib only; keys percent-encode into the
    URL path; an optional access key rides the Authorization header
    (``PIO_TPU_BLOB_ACCESS_KEY`` or ``http://host:port/prefix?accessKey=…``).
    """

    def __init__(self, base_url: str, access_key: Optional[str] = None):
        from urllib.parse import parse_qs, urlsplit, urlunsplit

        parts = urlsplit(base_url)
        if access_key is None:
            qs = parse_qs(parts.query)
            access_key = (qs.get("accessKey") or [None])[0]
            if access_key is None:
                access_key = knobs.knob_raw("PIO_TPU_BLOB_ACCESS_KEY")
        self._key_hdr = access_key
        self.base = urlunsplit(
            (parts.scheme, parts.netloc, parts.path.rstrip("/"), "", "")
        )

    def _request(self, method: str, url: str, data: Optional[bytes] = None):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(url, data=data, method=method)
        if data is not None:
            req.add_header("Content-Type", "application/octet-stream")
        if self._key_hdr:
            req.add_header("Authorization", f"Bearer {self._key_hdr}")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return 404, b""
            raise base.StorageError(
                f"blob server {method} {url}: HTTP {e.code} "
                f"{e.read()[:200]!r}"
            )
        except urllib.error.URLError as e:
            raise base.StorageError(f"blob server unreachable: {e}")

    def _url(self, key: str) -> str:
        return f"{self.base}/blobs/{quote(key, safe='/')}"

    def put(self, key: str, data: bytes) -> None:
        status, _ = self._request("PUT", self._url(key), data)
        if status not in (200, 201):
            raise base.StorageError(f"blob put failed: HTTP {status}")

    def get(self, key: str) -> Optional[bytes]:
        status, data = self._request("GET", self._url(key))
        return None if status == 404 else data

    def delete(self, key: str) -> bool:
        status, _ = self._request("DELETE", self._url(key))
        return status != 404

    def exists(self, key: str) -> bool:
        status, _ = self._request("HEAD", self._url(key))
        return status != 404

    def list(self, prefix: str) -> List[str]:
        import json as _json
        from urllib.parse import quote as _q

        status, data = self._request(
            "GET", f"{self.base}/keys?prefix={_q(prefix, safe='')}"
        )
        if status == 404:
            return []
        return _json.loads(data.decode("utf-8"))["keys"]


#: scheme → factory(netloc_and_path) (the gs://, s3://, hdfs:// plug point)
_SCHEMES: Dict[str, Callable[[str], BlobBackend]] = {}


def register_blob_scheme(
    scheme: str, factory: Callable[[str], BlobBackend]
) -> None:
    _SCHEMES[scheme.lower()] = factory


register_blob_scheme("file", FileBlobBackend)
register_blob_scheme("http", lambda loc: HTTPBlobBackend(f"http://{loc}"))
register_blob_scheme("https", lambda loc: HTTPBlobBackend(f"https://{loc}"))


def open_blob_backend(uri: str) -> BlobBackend:
    """URI → backend. ``file:///path`` and bare paths ship today; other
    schemes resolve through the registry so a gs/s3/hdfs client can be
    plugged in without touching the Models trait."""
    parsed = urlparse(uri)
    scheme = (parsed.scheme or "file").lower()
    factory = _SCHEMES.get(scheme)
    if factory is None:
        raise base.StorageError(
            f"no blob backend registered for scheme {scheme!r} "
            f"(register one with pio_tpu.storage.blobstore."
            f"register_blob_scheme)"
        )
    if scheme == "file":
        # file://HOST/path has no meaning here; accept file:///abs and bare
        location = parsed.path or uri
    else:
        location = (parsed.netloc + parsed.path).rstrip("/")
        if parsed.query:  # e.g. http://host:port/prefix?accessKey=…
            location += f"?{parsed.query}"
    return factory(location)


class BlobModels(base.Models):
    """Models trait over content-addressed blobs.

    ``objects/<aa>/<sha256>`` immutable blob; ``refs/<model-id>`` names
    the current digest (percent-encoded, so distinct ids can't collide).
    Reads verify the digest end-to-end; overwrites and deletes ref-count
    garbage-collect unreferenced objects.

    Concurrency: writes are safe per-key (atomic replace), and insert
    heals the dedupe/gc race by re-verifying its object after the ref
    write. A delete() on one process racing an insert() of the SAME bytes
    on another still has a tiny window to orphan the new ref — the same
    no-coordination contract the reference's HDFS/S3 stores have; get()
    then fails loudly ("referenced blob is missing") and a re-insert
    heals it.
    """

    def __init__(self, backend: BlobBackend):
        self._b = backend

    @staticmethod
    def _obj_key(digest: str) -> str:
        return f"objects/{digest[:2]}/{digest}"

    @staticmethod
    def _ref_key(model_id: str) -> str:
        # percent-encoding is injective — 'a/b' and 'a_b' must not share a
        # ref (a '/'-collapsing scheme would silently serve wrong bytes)
        return f"refs/{quote(model_id, safe='')}"

    def insert(self, model: Model) -> None:
        digest = hashlib.sha256(model.models).hexdigest()
        obj = self._obj_key(digest)
        old_ref = self._b.get(self._ref_key(model.id))
        # unconditional put (objects are immutable, re-put is an atomic
        # replace of identical bytes) narrows the window against a
        # concurrent delete()'s gc; see class docstring for the residual
        # cross-process caveat
        self._b.put(obj, model.models)
        self._b.put(self._ref_key(model.id), digest.encode("ascii"))
        if not self._b.exists(obj):  # gc raced us: heal the dangling ref
            self._b.put(obj, model.models)
        if old_ref is not None:
            old_digest = old_ref.decode("ascii").strip()
            if old_digest != digest:  # overwrite must not leak v1's blob
                self._gc_if_unreferenced(old_digest)

    def get(self, model_id: str) -> Optional[Model]:
        ref = self._b.get(self._ref_key(model_id))
        if ref is None:
            return None
        digest = ref.decode("ascii").strip()
        data = self._b.get(self._obj_key(digest))
        if data is None:
            raise base.StorageError(
                f"model {model_id!r}: referenced blob {digest} is missing"
            )
        actual = hashlib.sha256(data).hexdigest()
        if actual != digest:
            raise base.StorageError(
                f"model {model_id!r}: blob digest mismatch "
                f"(expected {digest}, got {actual}) — corrupt object store"
            )
        return Model(model_id, data)

    def _gc_if_unreferenced(self, digest: str) -> None:
        """Drop an object no ref names anymore (ref-count scan)."""
        still_referenced = any(
            (r := self._b.get(k)) is not None
            and r.decode("ascii").strip() == digest
            for k in self._b.list("refs")
        )
        if not still_referenced:
            self._b.delete(self._obj_key(digest))

    def delete(self, model_id: str) -> bool:
        ref_key = self._ref_key(model_id)
        ref = self._b.get(ref_key)
        if ref is None:
            return False
        digest = ref.decode("ascii").strip()
        self._b.delete(ref_key)
        self._gc_if_unreferenced(digest)
        return True
