"""Content-addressed blob Models store — the HDFS/S3 slot.

The reference ships two remote model stores (``storage/hdfs/.../
HDFSModels.scala``, ``storage/s3/.../S3Models.scala`` — UNVERIFIED paths;
SURVEY.md §2.3) that write one opaque file per engine-instance id into a
cluster filesystem. This rebuild generalizes the slot instead of binding
to one vendor client:

- A tiny **BlobBackend SPI** (put/get/delete/exists on flat keys) keyed by
  URI scheme. ``file://`` ships today; ``gs://``/``s3://``/``hdfs://``
  plug in by registering a backend for their scheme
  (:func:`register_blob_scheme`) — the Models trait above them does not
  change.
- **Content addressing**: blobs live at ``objects/<aa>/<sha256>`` and a
  mutable ``refs/<model-id>`` pointer names the current blob. Identical
  models dedupe, every read is digest-verified end-to-end (a corrupt or
  torn remote object is an error, not a silently wrong model), and a
  model artifact can be mirrored between stores by copying immutable
  objects without rewriting metadata.

Select it with::

    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=BLOB
    PIO_STORAGE_SOURCES_BLOB_TYPE=blob
    PIO_STORAGE_SOURCES_BLOB_PATH=file:///var/pio/models   # or gs://bucket/prefix
"""

from __future__ import annotations

import abc
import hashlib
import os
from typing import Callable, Dict, List, Optional
from urllib.parse import quote, urlparse

from pio_tpu.storage import base
from pio_tpu.storage.records import Model


class BlobBackend(abc.ABC):
    """Flat key → bytes store (the part a gs://, s3://, or hdfs:// client
    must implement; keys use '/' separators and are safe path segments)."""

    @abc.abstractmethod
    def put(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...

    @abc.abstractmethod
    def exists(self, key: str) -> bool: ...

    @abc.abstractmethod
    def list(self, prefix: str) -> List[str]:
        """Keys under a prefix (used by ref-count garbage collection)."""


class FileBlobBackend(BlobBackend):
    """file:// — atomic single-file objects under a root directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.normpath(os.path.join(self.root, key))
        if not p.startswith(os.path.normpath(self.root) + os.sep):
            raise base.StorageError(f"blob key escapes the root: {key!r}")
        return p

    def put(self, key: str, data: bytes) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = f"{p}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def get(self, key: str) -> Optional[bytes]:
        p = self._path(key)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def delete(self, key: str) -> bool:
        p = self._path(key)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def list(self, prefix: str) -> List[str]:
        base_dir = self._path(prefix) if prefix else self.root
        out = []
        for dirpath, _dirs, files in os.walk(base_dir):
            for f in files:
                full = os.path.join(dirpath, f)
                out.append(os.path.relpath(full, self.root).replace(
                    os.sep, "/"
                ))
        return out


#: scheme → factory(netloc_and_path) (the gs://, s3://, hdfs:// plug point)
_SCHEMES: Dict[str, Callable[[str], BlobBackend]] = {}


def register_blob_scheme(
    scheme: str, factory: Callable[[str], BlobBackend]
) -> None:
    _SCHEMES[scheme.lower()] = factory


register_blob_scheme("file", FileBlobBackend)


def open_blob_backend(uri: str) -> BlobBackend:
    """URI → backend. ``file:///path`` and bare paths ship today; other
    schemes resolve through the registry so a gs/s3/hdfs client can be
    plugged in without touching the Models trait."""
    parsed = urlparse(uri)
    scheme = (parsed.scheme or "file").lower()
    factory = _SCHEMES.get(scheme)
    if factory is None:
        raise base.StorageError(
            f"no blob backend registered for scheme {scheme!r} "
            f"(register one with pio_tpu.storage.blobstore."
            f"register_blob_scheme)"
        )
    if scheme == "file":
        # file://HOST/path has no meaning here; accept file:///abs and bare
        location = parsed.path or uri
    else:  # pragma: no cover - exercised by third-party backends
        location = (parsed.netloc + parsed.path).rstrip("/")
    return factory(location)


class BlobModels(base.Models):
    """Models trait over content-addressed blobs.

    ``objects/<aa>/<sha256>`` immutable blob; ``refs/<model-id>`` names
    the current digest (percent-encoded, so distinct ids can't collide).
    Reads verify the digest end-to-end; overwrites and deletes ref-count
    garbage-collect unreferenced objects.

    Concurrency: writes are safe per-key (atomic replace), and insert
    heals the dedupe/gc race by re-verifying its object after the ref
    write. A delete() on one process racing an insert() of the SAME bytes
    on another still has a tiny window to orphan the new ref — the same
    no-coordination contract the reference's HDFS/S3 stores have; get()
    then fails loudly ("referenced blob is missing") and a re-insert
    heals it.
    """

    def __init__(self, backend: BlobBackend):
        self._b = backend

    @staticmethod
    def _obj_key(digest: str) -> str:
        return f"objects/{digest[:2]}/{digest}"

    @staticmethod
    def _ref_key(model_id: str) -> str:
        # percent-encoding is injective — 'a/b' and 'a_b' must not share a
        # ref (a '/'-collapsing scheme would silently serve wrong bytes)
        return f"refs/{quote(model_id, safe='')}"

    def insert(self, model: Model) -> None:
        digest = hashlib.sha256(model.models).hexdigest()
        obj = self._obj_key(digest)
        old_ref = self._b.get(self._ref_key(model.id))
        # unconditional put (objects are immutable, re-put is an atomic
        # replace of identical bytes) narrows the window against a
        # concurrent delete()'s gc; see class docstring for the residual
        # cross-process caveat
        self._b.put(obj, model.models)
        self._b.put(self._ref_key(model.id), digest.encode("ascii"))
        if not self._b.exists(obj):  # gc raced us: heal the dangling ref
            self._b.put(obj, model.models)
        if old_ref is not None:
            old_digest = old_ref.decode("ascii").strip()
            if old_digest != digest:  # overwrite must not leak v1's blob
                self._gc_if_unreferenced(old_digest)

    def get(self, model_id: str) -> Optional[Model]:
        ref = self._b.get(self._ref_key(model_id))
        if ref is None:
            return None
        digest = ref.decode("ascii").strip()
        data = self._b.get(self._obj_key(digest))
        if data is None:
            raise base.StorageError(
                f"model {model_id!r}: referenced blob {digest} is missing"
            )
        actual = hashlib.sha256(data).hexdigest()
        if actual != digest:
            raise base.StorageError(
                f"model {model_id!r}: blob digest mismatch "
                f"(expected {digest}, got {actual}) — corrupt object store"
            )
        return Model(model_id, data)

    def _gc_if_unreferenced(self, digest: str) -> None:
        """Drop an object no ref names anymore (ref-count scan)."""
        still_referenced = any(
            (r := self._b.get(k)) is not None
            and r.decode("ascii").strip() == digest
            for k in self._b.list("refs")
        )
        if not still_referenced:
            self._b.delete(self._obj_key(digest))

    def delete(self, model_id: str) -> bool:
        ref_key = self._ref_key(model_id)
        ref = self._b.get(ref_key)
        if ref is None:
            return False
        digest = ref.decode("ascii").strip()
        self._b.delete(ref_key)
        self._gc_if_unreferenced(digest)
        return True
