"""Storage layer: SPI traits, backends, registry, columnar event frames.

Rebuild of the reference's storage subsystem (``data/.../data/storage/`` +
``storage/*`` subprojects — UNVERIFIED paths; see SURVEY.md). Backends:
in-memory (tests/ephemeral), SQLite (quickstart default ≙ reference JDBC),
Parquet shards (bulk/training ≙ reference HBase), LocalFS model blobs.
"""

from pio_tpu.storage.base import (
    AccessKeys,
    Apps,
    Channels,
    EngineInstances,
    EvaluationInstances,
    LEvents,
    Models,
    PEvents,
    StorageError,
)
from pio_tpu.storage.frame import EventFrame
from pio_tpu.storage.records import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
    RunStatus,
)
from pio_tpu.storage.registry import Storage, StorageConfigError, pio_home

__all__ = [
    "AccessKey",
    "AccessKeys",
    "App",
    "Apps",
    "Channel",
    "Channels",
    "EngineInstance",
    "EngineInstances",
    "EvaluationInstance",
    "EvaluationInstances",
    "EventFrame",
    "LEvents",
    "Model",
    "Models",
    "PEvents",
    "RunStatus",
    "Storage",
    "StorageConfigError",
    "StorageError",
    "pio_home",
]
