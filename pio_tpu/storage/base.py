"""Storage SPI — abstract interfaces every backend implements.

Rebuild of the reference's storage traits (``data/.../data/storage/
{LEvents,PEvents,Apps,AccessKeys,Channels,EngineInstances,
EvaluationInstances,Models}.scala`` — UNVERIFIED paths; see SURVEY.md).

Two event access styles, as in the reference:

- :class:`LEvents` — single-row CRUD + filtered scans; the low-latency,
  serving-side path (Event Server inserts, feedback loop reads).
- :class:`PEvents` — bulk access for training; where the reference
  materializes Spark ``RDD[Event]``, we materialize a columnar
  :class:`~pio_tpu.storage.frame.EventFrame` whose numeric columns become
  (host-shardable) device arrays.

A backend may implement both over the same underlying store (SQLite and
memory backends do); Parquet implements the bulk path natively.
"""

from __future__ import annotations

import abc
import datetime as _dt
from typing import Iterable, List, Optional, Sequence, Tuple

from pio_tpu.data.datamap import PropertyMap
from pio_tpu.data.event import Event
from pio_tpu.storage.records import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

#: channel_id None == default channel (reference uses Option[Int]).
ChannelId = Optional[int]


class StorageError(RuntimeError):
    pass


def _aggregate_via_find(
    find,
    app_id: int,
    entity_type: str,
    channel_id: ChannelId,
    start_time,
    until_time,
    required,
) -> dict:
    """Shared fold behind LEvents/PEvents.aggregate_properties."""
    from pio_tpu.data.aggregation import aggregate_properties as _agg
    from pio_tpu.data.event import SPECIAL_EVENTS

    events = find(
        app_id,
        channel_id=channel_id,
        start_time=start_time,
        until_time=until_time,
        entity_type=entity_type,
        event_names=sorted(SPECIAL_EVENTS),
    )
    folded = _agg(events)
    out = {eid: pm for (etype, eid), pm in folded.items() if etype == entity_type}
    if required:
        req = set(required)
        out = {k: v for k, v in out.items() if req.issubset(v.keys())}
    return out


class LEvents(abc.ABC):
    """Single-event CRUD + query (reference trait ``LEvents``)."""

    @abc.abstractmethod
    def init_channel(self, app_id: int, channel_id: ChannelId = None) -> bool:
        """Prepare storage for an (app, channel); idempotent."""

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: ChannelId = None) -> str:
        """Insert one event; returns the (possibly generated) event id."""

    def insert_batch(
        self, events: List[Event], app_id: int,
        channel_id: ChannelId = None,
    ) -> List[str]:
        """Insert many events, returning their ids in order.

        Default loops :meth:`insert`; backends with a cheaper bulk path
        (one transaction/commit instead of one per event — the sqlite
        backend measures ~4× on the batch ingest route) override it. The
        reference's ``/batch/events.json`` is the consumer.
        """
        return [self.insert(e, app_id, channel_id) for e in events]

    @abc.abstractmethod
    def get(
        self, event_id: str, app_id: int, channel_id: ChannelId = None
    ) -> Optional[Event]: ...

    @abc.abstractmethod
    def delete(
        self, event_id: str, app_id: int, channel_id: ChannelId = None
    ) -> bool: ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: ChannelId = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> List[Event]:
        """Filtered scan ordered by event time (desc when ``reversed_order``).

        ``limit=None`` means no limit; the reference's ``limit=-1`` maps to
        ``None`` here.
        """

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: ChannelId = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> dict:
        """Fold special events into per-entity PropertyMaps.

        Default implementation on top of :meth:`find`, as the reference's
        ``LEventAggregator`` does; backends may override with a pushed-down
        version. Returns {entity_id: PropertyMap}.
        """
        return _aggregate_via_find(
            self.find, app_id, entity_type, channel_id, start_time, until_time,
            required,
        )

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: ChannelId = None) -> bool:
        """Drop all events for (app, channel)."""

    def close(self) -> None:
        pass


class PEvents(abc.ABC):
    """Bulk event access for training (reference trait ``PEvents``).

    The reference returns ``RDD[Event]``; we return either a Python list
    (:meth:`find`) or a columnar :class:`EventFrame` (:meth:`find_frame`)
    ready to become device arrays.
    """

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: ChannelId = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
    ) -> List[Event]: ...

    def find_frame(self, app_id: int, **filters):
        """Columnar bulk read. Default: build from :meth:`find`."""
        from pio_tpu.storage.frame import EventFrame

        return EventFrame.from_events(self.find(app_id, **filters))

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: ChannelId = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> dict:
        return _aggregate_via_find(
            self.find, app_id, entity_type, channel_id, start_time, until_time,
            required,
        )

    @abc.abstractmethod
    def write(
        self, events: Iterable[Event], app_id: int, channel_id: ChannelId = None
    ) -> None:
        """Bulk append (reference ``PEvents.write``)."""

    @abc.abstractmethod
    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: ChannelId = None
    ) -> None:
        """Bulk delete by event id (reference ``PEvents.delete``)."""


class PEventsAdapter(PEvents):
    """PEvents facade over a combined LEvents+bulk backend.

    Needed because ``PEvents.delete`` (bulk, by id list) clashes with
    ``LEvents.delete`` (single id) on classes implementing both; backends
    expose the bulk variant as ``delete_bulk`` and this adapter maps it to
    the SPI name.
    """

    def __init__(self, backend):
        self._b = backend

    def find(self, app_id, channel_id=None, **filters) -> List[Event]:
        return self._b.find(app_id, channel_id=channel_id, **filters)

    def find_frame(self, app_id, **filters):
        from pio_tpu.storage.frame import EventFrame

        if hasattr(self._b, "find_frame"):
            return self._b.find_frame(app_id, **filters)
        return EventFrame.from_events(self.find(app_id, **filters))

    def aggregate_properties(self, app_id, entity_type, channel_id=None,
                             start_time=None, until_time=None,
                             required=None) -> dict:
        # a backend that pushed the fold down (e.g. the partitioned log's
        # snapshot-aware read) must keep that advantage on the bulk path
        if type(self._b).aggregate_properties is not LEvents.aggregate_properties:
            return self._b.aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
                required=required,
            )
        return super().aggregate_properties(
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required,
        )

    def write(self, events, app_id, channel_id=None) -> None:
        self._b.write(events, app_id, channel_id)

    def delete(self, event_ids, app_id, channel_id=None) -> None:
        self._b.delete_bulk(event_ids, app_id, channel_id)


# ----------------------------------------------------------------- meta data
class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]:
        """Insert; app.id==0 means auto-assign. Returns assigned id."""

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> List[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> bool: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> bool: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> Optional[str]:
        """Insert; empty key means generate. Returns the key string."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[AccessKey]: ...

    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> bool: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...


class Channels(abc.ABC):
    @abc.abstractmethod
    def insert(self, channel: Channel) -> Optional[int]:
        """Insert; channel.id==0 means auto-assign. Returns assigned id."""

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> bool: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str:
        """Insert; empty id means generate. Returns id."""

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> bool: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> bool: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class Models(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, model_id: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, model_id: str) -> bool: ...
