"""SQLite storage backend — the quickstart default.

Plays the role of the reference's JDBC/PostgreSQL backend
(``storage/jdbc/src/main/scala/o/a/p/data/storage/jdbc/*`` — UNVERIFIED
path; see SURVEY.md): implements every SPI trait over a single SQLite file.
Connections are per-thread (sqlite3 objects can't cross threads); WAL mode
keeps concurrent server reads cheap.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import sqlite3
import threading
import uuid
from typing import Iterable, List, Optional, Sequence

from pio_tpu.data.datamap import DataMap
from pio_tpu.data.event import Event
from pio_tpu.storage import base
from pio_tpu.storage.records import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS events (
  id TEXT NOT NULL,
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL DEFAULT 0,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL,
  event_time_us INTEGER NOT NULL,
  tags TEXT NOT NULL,
  pr_id TEXT,
  creation_time_us INTEGER NOT NULL,
  PRIMARY KEY (app_id, channel_id, id)
);
CREATE INDEX IF NOT EXISTS idx_events_scan
  ON events (app_id, channel_id, event_time_us);
CREATE INDEX IF NOT EXISTS idx_events_entity
  ON events (app_id, channel_id, entity_type, entity_id);
CREATE TABLE IF NOT EXISTS apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT
);
CREATE TABLE IF NOT EXISTS access_keys (
  key TEXT PRIMARY KEY,
  app_id INTEGER NOT NULL,
  events TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS channels (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  app_id INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS engine_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time_us INTEGER NOT NULL,
  end_time_us INTEGER NOT NULL,
  engine_id TEXT NOT NULL,
  engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL,
  engine_factory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  jax_conf TEXT NOT NULL DEFAULT '{}',
  data_source_params TEXT NOT NULL DEFAULT '{}',
  preparator_params TEXT NOT NULL DEFAULT '{}',
  algorithms_params TEXT NOT NULL DEFAULT '[]',
  serving_params TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time_us INTEGER NOT NULL,
  end_time_us INTEGER NOT NULL,
  evaluation_class TEXT NOT NULL DEFAULT '',
  engine_params_generator_class TEXT NOT NULL DEFAULT '',
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  evaluator_results TEXT NOT NULL DEFAULT '',
  evaluator_results_html TEXT NOT NULL DEFAULT '',
  evaluator_results_json TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS models (
  id TEXT PRIMARY KEY,
  models BLOB NOT NULL
);
"""

from pio_tpu.utils.timeutil import from_micros as _from_us, to_micros as _to_us

#: bump when _SCHEMA changes shape, and add a migration step below
#: (reference analog: `pio upgrade` migrating storage between releases)
SCHEMA_VERSION = 1

#: from-version → LIST of single SQL statements bringing the db to
#: from-version + 1. Statement lists (not scripts): sqlite3's
#: executescript() force-commits, which would break the per-step
#: transaction that makes a failed migration roll back cleanly.
MIGRATIONS: dict = {}


class SQLiteClient:
    """Per-thread connections to one SQLite file (or shared memory db)."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()
        self._init_lock = threading.Lock()
        with self._init_lock:
            conn = self.conn()
            # the DDL commit inside _migrate is the same one-shot
            # migration the suppression below covers
            self._migrate(conn)  # pio: disable=lock-blocking-call
            # one-shot schema migration: serializing the commit is the
            # point (concurrent first-openers must not race the DDL)
            conn.commit()  # pio: disable=lock-blocking-call

    @staticmethod
    def _migrate(conn) -> None:
        """Create or upgrade the schema, stamped via PRAGMA user_version.

        Version 0 covers both fresh files and pre-versioning databases;
        the CREATE IF NOT EXISTS script is idempotent over the latter.
        A FILE NEWER than this build refuses to open (no downgrades).
        """
        v = conn.execute("PRAGMA user_version").fetchone()[0]
        if v > SCHEMA_VERSION:
            raise base.StorageError(
                f"database schema v{v} is newer than this build's "
                f"v{SCHEMA_VERSION}; upgrade pio-tpu instead"
            )
        if v == 0:
            pre_versioning = conn.execute(
                "SELECT 1 FROM sqlite_master WHERE type='table' "
                "AND name='events'"
            ).fetchone()
            if pre_versioning:
                # tables from a pre-versioning build: stamp v1 (their
                # shape) and fall through the ladder like any old db
                conn.execute("PRAGMA user_version = 1")
                conn.commit()
                v = 1
            else:
                # fresh file: current schema directly, no ladder
                conn.executescript(_SCHEMA)
                conn.execute(f"PRAGMA user_version = {SCHEMA_VERSION}")
                conn.commit()
                return
        for step in range(v, SCHEMA_VERSION):
            if step not in MIGRATIONS:
                raise base.StorageError(
                    f"no migration registered for schema v{step} → "
                    f"v{step + 1} (SCHEMA_VERSION bumped without a "
                    "MIGRATIONS entry)"
                )
            # one transaction per step, stamped inside it: a failure rolls
            # the step back whole, and a concurrent migrator blocks on
            # BEGIN IMMEDIATE then re-reads the version it races with
            conn.commit()  # close any implicit transaction first
            conn.execute("BEGIN IMMEDIATE")
            try:
                cur = conn.execute("PRAGMA user_version").fetchone()[0]
                if cur != step:  # someone else already applied this step
                    conn.rollback()
                    continue
                for stmt in MIGRATIONS[step]:
                    conn.execute(stmt)
                conn.execute(f"PRAGMA user_version = {step + 1}")
                conn.commit()
            except BaseException:
                conn.rollback()
                raise

    @staticmethod
    def schema_version(conn) -> int:
        return conn.execute("PRAGMA user_version").fetchone()[0]

    def conn(self) -> sqlite3.Connection:
        c = getattr(self._local, "conn", None)
        if c is None:
            try:
                c = sqlite3.connect(self.path, timeout=30.0)
            except sqlite3.OperationalError:
                # self-heal a vanished parent directory (cleanup/rotation
                # under a long-running server) instead of failing every
                # request until restart
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                c = sqlite3.connect(self.path, timeout=30.0)
            c.execute("PRAGMA journal_mode=WAL")
            # the durability knob maps onto SQLite's sync levels: commit
            # = FULL (fsync per txn), batch = NORMAL (WAL fsyncs at
            # checkpoint), os = OFF (page cache only)
            from pio_tpu.storage.durability import mode as _durability

            sync = {"commit": "FULL", "batch": "NORMAL", "os": "OFF"}[
                _durability()
            ]
            c.execute(f"PRAGMA synchronous={sync}")
            # in-engine busy handler alongside the connect timeout: a
            # statement hitting SQLITE_BUSY retries inside sqlite before
            # surfacing OperationalError (which retrying() then treats
            # as transient)
            c.execute("PRAGMA busy_timeout=30000")
            # default checkpoint-every-1000-pages runs mid-commit on the
            # ingest hot path (measured ~2x per-insert cost); 16384 pages
            # (~64 MB WAL ceiling) amortizes it — readers are unaffected,
            # the WAL is part of the database
            c.execute("PRAGMA wal_autocheckpoint=16384")
            self._local.conn = c
        return c

    def close(self):
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
            self._local.conn = None


def _chan(channel_id) -> int:
    return 0 if channel_id is None else int(channel_id)


def _row_to_event(r) -> Event:
    return Event(
        event=r[3],
        entity_type=r[4],
        entity_id=r[5],
        target_entity_type=r[6],
        target_entity_id=r[7],
        properties=DataMap._wrap(json.loads(r[8])),
        event_time=_from_us(r[9]),
        tags=tuple(json.loads(r[10])),
        pr_id=r[11],
        event_id=r[0],
        creation_time=_from_us(r[12]),
    )


_EVENT_INSERT_SQL = (
    "INSERT OR REPLACE INTO events VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)"
)


class SQLiteEvents(base.LEvents, base.PEvents):
    """LEvents + PEvents over the ``events`` table."""

    def __init__(self, client: SQLiteClient):
        self._c = client
        # the group committer must coalesce across REQUESTS, and the
        # registry builds a fresh wrapper per get_levents() call — so it
        # lives on the (cached, shared) client, created once
        gc = getattr(client, "_events_gc", None)
        if gc is None:
            with client._init_lock:
                gc = getattr(client, "_events_gc", None)
                if gc is None:
                    from pio_tpu.storage.groupcommit import GroupCommitter

                    def flush(payloads):
                        from pio_tpu.faults import failpoint

                        conn = client.conn()
                        try:
                            conn.executemany(
                                _EVENT_INSERT_SQL,
                                [p[1] for p in payloads],
                            )
                            # between executemany and commit: an error
                            # here proves the rollback keeps the thread-
                            # local connection clean; a crash proves WAL
                            # recovery drops the uncommitted txn whole
                            failpoint("storage.sqlite.commit")
                            conn.commit()
                        except Exception:
                            # leave nothing pending on the thread-local
                            # connection — an unrolled-back partial
                            # executemany would ride out with the next
                            # unrelated commit despite the client 500
                            conn.rollback()
                            raise
                        return [p[0] for p in payloads]

                    gc = GroupCommitter(flush, store="sqlite")
                    client._events_gc = gc
        self._gc = gc

    def init_channel(self, app_id, channel_id=None) -> bool:
        return True  # single-table design; nothing to create

    @staticmethod
    def _row(eid: str, event: Event, app_id, channel_id):
        return (
            eid,
            app_id,
            _chan(channel_id),
            event.event,
            event.entity_type,
            event.entity_id,
            event.target_entity_type,
            event.target_entity_id,
            json.dumps(event.properties.to_dict()),
            _to_us(event.event_time),
            json.dumps(list(event.tags)),
            event.pr_id,
            _to_us(event.creation_time),
        )

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        """Single insert via GROUP COMMIT: concurrent single-event
        ingests coalesce into one executemany + one WAL commit (the
        leader/follower protocol in storage/groupcommit.py — free for
        serial traffic, amortized commits under concurrent POSTs)."""
        eid = event.event_id or Event.new_event_id()
        return self._gc.submit(
            (eid, self._row(eid, event, app_id, channel_id))
        )

    def insert_batch(self, events, app_id, channel_id=None):
        """One executemany + one commit for the whole batch (the WAL
        commit per event dominates per-event cost; amortizing it across
        ≤50 events is the batch route's whole point)."""
        ids = [e.event_id or Event.new_event_id() for e in events]
        conn = self._c.conn()
        conn.executemany(
            _EVENT_INSERT_SQL,
            [
                self._row(eid, e, app_id, channel_id)
                for eid, e in zip(ids, events)
            ],
        )
        conn.commit()
        return ids

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        cur = self._c.conn().execute(
            "SELECT * FROM events WHERE app_id=? AND channel_id=? AND id=?",
            (app_id, _chan(channel_id), event_id),
        )
        r = cur.fetchone()
        return _row_to_event(r) if r else None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        conn = self._c.conn()
        cur = conn.execute(
            "DELETE FROM events WHERE app_id=? AND channel_id=? AND id=?",
            (app_id, _chan(channel_id), event_id),
        )
        conn.commit()
        return cur.rowcount > 0

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=None,
        target_entity_id=None,
        limit=None,
        reversed_order=False,
    ) -> List[Event]:
        sql = ["SELECT * FROM events WHERE app_id=? AND channel_id=?"]
        args: list = [app_id, _chan(channel_id)]
        if start_time is not None:
            sql.append("AND event_time_us >= ?")
            args.append(_to_us(start_time))
        if until_time is not None:
            sql.append("AND event_time_us < ?")
            args.append(_to_us(until_time))
        if entity_type is not None:
            sql.append("AND entity_type = ?")
            args.append(entity_type)
        if entity_id is not None:
            sql.append("AND entity_id = ?")
            args.append(entity_id)
        if event_names is not None:
            qs = ",".join("?" * len(list(event_names)))
            sql.append(f"AND event IN ({qs})")
            args.extend(event_names)
        if target_entity_type is not None:
            sql.append("AND target_entity_type = ?")
            args.append(target_entity_type)
        if target_entity_id is not None:
            sql.append("AND target_entity_id = ?")
            args.append(target_entity_id)
        sql.append(
            "ORDER BY event_time_us DESC" if reversed_order else "ORDER BY event_time_us ASC"
        )
        if limit is not None and limit >= 0:
            sql.append("LIMIT ?")
            args.append(limit)
        cur = self._c.conn().execute(" ".join(sql), args)
        return [_row_to_event(r) for r in cur.fetchall()]

    def remove(self, app_id, channel_id=None) -> bool:
        conn = self._c.conn()
        conn.execute(
            "DELETE FROM events WHERE app_id=? AND channel_id=?",
            (app_id, _chan(channel_id)),
        )
        conn.commit()
        return True

    # -- PEvents ------------------------------------------------------------
    def write(self, events: Iterable[Event], app_id, channel_id=None) -> None:
        conn = self._c.conn()
        rows = []
        for event in events:
            eid = event.event_id or Event.new_event_id()
            rows.append(
                (
                    eid,
                    app_id,
                    _chan(channel_id),
                    event.event,
                    event.entity_type,
                    event.entity_id,
                    event.target_entity_type,
                    event.target_entity_id,
                    json.dumps(event.properties.to_dict()),
                    _to_us(event.event_time),
                    json.dumps(list(event.tags)),
                    event.pr_id,
                    _to_us(event.creation_time),
                )
            )
        conn.executemany(
            "INSERT OR REPLACE INTO events VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)", rows
        )
        conn.commit()

    def delete_bulk(self, event_ids, app_id, channel_id=None) -> None:
        conn = self._c.conn()
        conn.executemany(
            "DELETE FROM events WHERE app_id=? AND channel_id=? AND id=?",
            [(app_id, _chan(channel_id), eid) for eid in event_ids],
        )
        conn.commit()

    def close(self) -> None:
        self._c.close()


# Shared facade mapping the bulk PEvents SPI onto the combined store.
SQLitePEvents = base.PEventsAdapter


class SQLiteApps(base.Apps):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, app: App) -> Optional[int]:
        conn = self._c.conn()
        try:
            if app.id:
                cur = conn.execute(
                    "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                    (app.id, app.name, app.description),
                )
            else:
                cur = conn.execute(
                    "INSERT INTO apps (name, description) VALUES (?,?)",
                    (app.name, app.description),
                )
            conn.commit()
            return cur.lastrowid if not app.id else app.id
        except sqlite3.IntegrityError:
            return None

    def get(self, app_id: int) -> Optional[App]:
        r = self._c.conn().execute(
            "SELECT id, name, description FROM apps WHERE id=?", (app_id,)
        ).fetchone()
        return App(*r) if r else None

    def get_by_name(self, name: str) -> Optional[App]:
        r = self._c.conn().execute(
            "SELECT id, name, description FROM apps WHERE name=?", (name,)
        ).fetchone()
        return App(*r) if r else None

    def get_all(self) -> List[App]:
        rows = self._c.conn().execute(
            "SELECT id, name, description FROM apps ORDER BY id"
        ).fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> bool:
        conn = self._c.conn()
        cur = conn.execute(
            "UPDATE apps SET name=?, description=? WHERE id=?",
            (app.name, app.description, app.id),
        )
        conn.commit()
        return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        conn = self._c.conn()
        cur = conn.execute("DELETE FROM apps WHERE id=?", (app_id,))
        conn.commit()
        return cur.rowcount > 0


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, access_key: AccessKey) -> Optional[str]:
        ak = access_key
        if not ak.key:
            ak = AccessKey.generate(ak.app_id, ak.events)
        conn = self._c.conn()
        try:
            conn.execute(
                "INSERT INTO access_keys VALUES (?,?,?)",
                (ak.key, ak.app_id, json.dumps(list(ak.events))),
            )
            conn.commit()
            return ak.key
        except sqlite3.IntegrityError:
            return None

    def _row(self, r) -> AccessKey:
        return AccessKey(r[0], r[1], tuple(json.loads(r[2])))

    def get(self, key: str) -> Optional[AccessKey]:
        r = self._c.conn().execute(
            "SELECT * FROM access_keys WHERE key=?", (key,)
        ).fetchone()
        return self._row(r) if r else None

    def get_all(self) -> List[AccessKey]:
        return [self._row(r) for r in self._c.conn().execute(
            "SELECT * FROM access_keys").fetchall()]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.conn()
            .execute("SELECT * FROM access_keys WHERE app_id=?", (app_id,))
            .fetchall()
        ]

    def update(self, access_key: AccessKey) -> bool:
        conn = self._c.conn()
        cur = conn.execute(
            "UPDATE access_keys SET app_id=?, events=? WHERE key=?",
            (access_key.app_id, json.dumps(list(access_key.events)), access_key.key),
        )
        conn.commit()
        return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        conn = self._c.conn()
        cur = conn.execute("DELETE FROM access_keys WHERE key=?", (key,))
        conn.commit()
        return cur.rowcount > 0


class SQLiteChannels(base.Channels):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        conn = self._c.conn()
        try:
            if channel.id:
                conn.execute(
                    "INSERT INTO channels (id, name, app_id) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.app_id),
                )
                conn.commit()
                return channel.id
            cur = conn.execute(
                "INSERT INTO channels (name, app_id) VALUES (?,?)",
                (channel.name, channel.app_id),
            )
            conn.commit()
            return cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def get(self, channel_id: int) -> Optional[Channel]:
        r = self._c.conn().execute(
            "SELECT id, name, app_id FROM channels WHERE id=?", (channel_id,)
        ).fetchone()
        return Channel(*r) if r else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        rows = self._c.conn().execute(
            "SELECT id, name, app_id FROM channels WHERE app_id=?", (app_id,)
        ).fetchall()
        return [Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        conn = self._c.conn()
        cur = conn.execute("DELETE FROM channels WHERE id=?", (channel_id,))
        conn.commit()
        return cur.rowcount > 0


class SQLiteEngineInstances(base.EngineInstances):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, instance: EngineInstance) -> str:
        iid = instance.id or uuid.uuid4().hex
        conn = self._c.conn()
        conn.execute(
            "INSERT OR REPLACE INTO engine_instances VALUES "
            "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                iid,
                instance.status,
                _to_us(instance.start_time),
                _to_us(instance.end_time),
                instance.engine_id,
                instance.engine_version,
                instance.engine_variant,
                instance.engine_factory,
                instance.batch,
                json.dumps(instance.env),
                json.dumps(instance.jax_conf),
                instance.data_source_params,
                instance.preparator_params,
                instance.algorithms_params,
                instance.serving_params,
            ),
        )
        conn.commit()
        return iid

    def _row(self, r) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=_from_us(r[2]),
            end_time=_from_us(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8],
            env=json.loads(r[9]),
            jax_conf=json.loads(r[10]),
            data_source_params=r[11],
            preparator_params=r[12],
            algorithms_params=r[13],
            serving_params=r[14],
        )

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        r = self._c.conn().execute(
            "SELECT * FROM engine_instances WHERE id=?", (instance_id,)
        ).fetchone()
        return self._row(r) if r else None

    def get_all(self) -> List[EngineInstance]:
        rows = self._c.conn().execute("SELECT * FROM engine_instances").fetchall()
        return [self._row(r) for r in rows]

    def get_completed(self, engine_id, engine_version, engine_variant):
        rows = self._c.conn().execute(
            "SELECT * FROM engine_instances WHERE status='COMPLETED' AND "
            "engine_id=? AND engine_version=? AND engine_variant=? "
            "ORDER BY start_time_us DESC",
            (engine_id, engine_version, engine_variant),
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        done = self.get_completed(engine_id, engine_version, engine_variant)
        return done[0] if done else None

    def update(self, instance: EngineInstance) -> bool:
        if self.get(instance.id) is None:
            return False
        self.insert(instance)
        return True

    def delete(self, instance_id: str) -> bool:
        conn = self._c.conn()
        cur = conn.execute(
            "DELETE FROM engine_instances WHERE id=?", (instance_id,)
        )
        conn.commit()
        return cur.rowcount > 0


class SQLiteEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, instance: EvaluationInstance) -> str:
        iid = instance.id or uuid.uuid4().hex
        conn = self._c.conn()
        conn.execute(
            "INSERT OR REPLACE INTO evaluation_instances VALUES "
            "(?,?,?,?,?,?,?,?,?,?,?)",
            (
                iid,
                instance.status,
                _to_us(instance.start_time),
                _to_us(instance.end_time),
                instance.evaluation_class,
                instance.engine_params_generator_class,
                instance.batch,
                json.dumps(instance.env),
                instance.evaluator_results,
                instance.evaluator_results_html,
                instance.evaluator_results_json,
            ),
        )
        conn.commit()
        return iid

    def _row(self, r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=_from_us(r[2]),
            end_time=_from_us(r[3]),
            evaluation_class=r[4],
            engine_params_generator_class=r[5],
            batch=r[6],
            env=json.loads(r[7]),
            evaluator_results=r[8],
            evaluator_results_html=r[9],
            evaluator_results_json=r[10],
        )

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        r = self._c.conn().execute(
            "SELECT * FROM evaluation_instances WHERE id=?", (instance_id,)
        ).fetchone()
        return self._row(r) if r else None

    def get_all(self) -> List[EvaluationInstance]:
        rows = self._c.conn().execute(
            "SELECT * FROM evaluation_instances"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(self) -> List[EvaluationInstance]:
        rows = self._c.conn().execute(
            "SELECT * FROM evaluation_instances WHERE status='COMPLETED' "
            "ORDER BY start_time_us DESC"
        ).fetchall()
        return [self._row(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> bool:
        if self.get(instance.id) is None:
            return False
        self.insert(instance)
        return True

    def delete(self, instance_id: str) -> bool:
        conn = self._c.conn()
        cur = conn.execute(
            "DELETE FROM evaluation_instances WHERE id=?", (instance_id,)
        )
        conn.commit()
        return cur.rowcount > 0


class SQLiteModels(base.Models):
    def __init__(self, client: SQLiteClient):
        self._c = client

    def insert(self, model: Model) -> None:
        conn = self._c.conn()
        conn.execute(
            "INSERT OR REPLACE INTO models VALUES (?,?)", (model.id, model.models)
        )
        conn.commit()

    def get(self, model_id: str) -> Optional[Model]:
        r = self._c.conn().execute(
            "SELECT id, models FROM models WHERE id=?", (model_id,)
        ).fetchone()
        return Model(r[0], r[1]) if r else None

    def delete(self, model_id: str) -> bool:
        conn = self._c.conn()
        cur = conn.execute("DELETE FROM models WHERE id=?", (model_id,))
        conn.commit()
        return cur.rowcount > 0
