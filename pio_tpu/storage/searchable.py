"""Searchable storage backend — the Elasticsearch-analog.

Fills SURVEY.md §2.3's "Elasticsearch (searchable meta store + events)"
slot (reference ``storage/elasticsearch/.../ESApps..ESLEvents..ESPEvents``,
UNVERIFIED paths). The reference delegates searchability to an external ES
cluster; the TPU-first rebuild keeps the capability in-process: SQLite FTS5
(BM25-ranked, unicode tokenizer) over the same file the relational tables
live in — no network service, same SPI, one extra capability:
``search(...)`` on events, apps, and run metadata.

Index maintenance is done by SQL **triggers**, not Python overrides, so
every write path (INSERT OR REPLACE upserts, bulk deletes, future verbs)
keeps the index consistent by construction. ``PRAGMA recursive_triggers``
is enabled per connection because REPLACE conflict resolution only fires
delete triggers with it on.

The indexed "body" of each row is a concatenation of its searchable
columns (including raw JSON text for properties/params — the FTS tokenizer
splits on punctuation, making JSON keys and values matchable terms).

Select it with::

    PIO_STORAGE_SOURCES_MYES_TYPE=searchable    # aliases: fts, elasticsearch
    PIO_STORAGE_SOURCES_MYES_PATH=/path/to/pio-search.db
"""

from __future__ import annotations

import sqlite3
from typing import List, Optional

from pio_tpu.storage import base
from pio_tpu.storage.records import App, EngineInstance, EvaluationInstance
from pio_tpu.storage.sqlite import (
    SQLiteApps,
    SQLiteClient,
    SQLiteEngineInstances,
    SQLiteEvaluationInstances,
    SQLiteEvents,
    _chan,
    _row_to_event,
)

#: body expressions per indexed table (also used by the trigger DDL and
#: the one-time backfill — single home so they cannot diverge)
_BODY = {
    "events": (
        "coalesce({p}.event,'') || ' ' || coalesce({p}.entity_type,'') || "
        "' ' || coalesce({p}.entity_id,'') || ' ' || "
        "coalesce({p}.target_entity_type,'') || ' ' || "
        "coalesce({p}.target_entity_id,'') || ' ' || "
        "coalesce({p}.properties,'') || ' ' || coalesce({p}.tags,'')"
    ),
    "apps": "coalesce({p}.name,'') || ' ' || coalesce({p}.description,'')",
    "engine_instances": (
        "coalesce({p}.id,'') || ' ' || coalesce({p}.status,'') || ' ' || "
        "coalesce({p}.engine_id,'') || ' ' || "
        "coalesce({p}.engine_factory,'') || ' ' || "
        "coalesce({p}.engine_variant,'') || ' ' || "
        "coalesce({p}.data_source_params,'') || ' ' || "
        "coalesce({p}.algorithms_params,'') || ' ' || "
        "coalesce({p}.serving_params,'')"
    ),
    "evaluation_instances": (
        "coalesce({p}.id,'') || ' ' || coalesce({p}.status,'') || ' ' || "
        "coalesce({p}.evaluation_class,'') || ' ' || "
        "coalesce({p}.engine_params_generator_class,'') || ' ' || "
        "coalesce({p}.evaluator_results,'')"
    ),
}


def _fts_ddl(table: str) -> List[str]:
    body_new = _BODY[table].format(p="new")
    return [
        f"CREATE VIRTUAL TABLE IF NOT EXISTS {table}_fts USING fts5(body)",
        f"""CREATE TRIGGER IF NOT EXISTS {table}_fts_ai
            AFTER INSERT ON {table} BEGIN
              INSERT INTO {table}_fts(rowid, body)
              VALUES (new.rowid, {body_new});
            END""",
        f"""CREATE TRIGGER IF NOT EXISTS {table}_fts_ad
            AFTER DELETE ON {table} BEGIN
              DELETE FROM {table}_fts WHERE rowid = old.rowid;
            END""",
        f"""CREATE TRIGGER IF NOT EXISTS {table}_fts_au
            AFTER UPDATE ON {table} BEGIN
              DELETE FROM {table}_fts WHERE rowid = old.rowid;
              INSERT INTO {table}_fts(rowid, body)
              VALUES (new.rowid, {body_new});
            END""",
    ]


class SearchableClient(SQLiteClient):
    """SQLiteClient + FTS5 index tables and sync triggers."""

    def __init__(self, path: str):
        super().__init__(path)
        conn = self.conn()
        for table in _BODY:
            for stmt in _fts_ddl(table):
                conn.execute(stmt)
            # adopt an existing plain-sqlite file: two-way sync of rows
            # written (or deleted) while no index/triggers existed.
            # Count-guarded so the common already-indexed open skips the
            # O(n) scan; OR IGNORE so two processes racing the first
            # adoption can't collide on duplicate FTS rowids; the DELETE
            # clears stale entries so the counts converge instead of
            # rescanning forever. (Open the same file as `searchable`
            # everywhere — a plain-sqlite writer on the side bypasses the
            # triggers between opens.)
            n_rows, n_idx = conn.execute(
                f"SELECT (SELECT count(*) FROM {table}), "
                f"(SELECT count(*) FROM {table}_fts)"
            ).fetchone()
            if n_rows != n_idx:
                conn.execute(
                    f"DELETE FROM {table}_fts WHERE rowid NOT IN "
                    f"(SELECT rowid FROM {table})"
                )
                conn.execute(
                    f"INSERT OR IGNORE INTO {table}_fts(rowid, body) "
                    f"SELECT t.rowid, {_BODY[table].format(p='t')} "
                    f"FROM {table} t WHERE t.rowid NOT IN "
                    f"(SELECT rowid FROM {table}_fts)"
                )
        conn.commit()

    def conn(self) -> sqlite3.Connection:
        fresh = getattr(self._local, "conn", None) is None
        c = super().conn()
        if fresh:
            # REPLACE-resolution deletes only fire the _ad triggers with
            # this on; per-connection, so set once when the thread-local
            # connection is created (close() → recreate re-applies it)
            c.execute("PRAGMA recursive_triggers=ON")
        return c

    def rebuild_index(self) -> None:
        """Drop and refill every FTS table from its base table.

        The index is keyed on sqlite's implicit rowid for tables without
        an INTEGER PRIMARY KEY (events has a composite PK; the instance
        tables have TEXT PKs), and ``VACUUM`` may renumber implicit
        rowids — silently desyncing the index in a way the count-based
        adoption guard in ``__init__`` cannot detect (counts still
        match). Any out-of-band ``VACUUM`` of the database file must be
        followed by this call. Nothing in-tree vacuums; this is the
        recovery hook for operators who do.
        """
        conn = self.conn()
        for table in _BODY:
            conn.execute(f"DELETE FROM {table}_fts")
            conn.execute(
                f"INSERT INTO {table}_fts(rowid, body) "
                f"SELECT t.rowid, {_BODY[table].format(p='t')} "
                f"FROM {table} t"
            )
        conn.commit()


class SearchError(base.StorageError):
    """Malformed FTS query string (surfaced with the sqlite detail)."""


def _match(conn, table: str, query: str, where: str, args: tuple,
           limit: Optional[int]):
    sql = (
        f"SELECT t.* FROM {table} t JOIN {table}_fts f ON t.rowid = f.rowid "
        f"WHERE {table}_fts MATCH ? {where} ORDER BY bm25({table}_fts)"
    )
    params: list = [query, *args]
    if limit is not None and limit >= 0:
        sql += " LIMIT ?"
        params.append(limit)
    try:
        return conn.execute(sql, params).fetchall()
    except sqlite3.OperationalError as e:
        # MATCH-parse failures are the caller's fault — 'fts5: syntax
        # error' for malformed expressions, 'no such column' for ES-style
        # field:term filters naming a non-column. Locks and other
        # infrastructure errors must propagate unblamed.
        msg = str(e).lower()
        if "fts5" in msg or "no such column" in msg:
            raise SearchError(f"bad search query {query!r}: {e}") from e
        raise


class SearchableEvents(SQLiteEvents):
    """LEvents/PEvents + BM25 full-text search over event bodies."""

    def search(
        self,
        app_id: int,
        query: str,
        channel_id=None,
        limit: Optional[int] = None,
    ):
        """Events of one app/channel matching an FTS5 query string
        (terms, ``AND``/``OR``/``NOT``, ``"phrases"``, ``prefix*``),
        best BM25 rank first."""
        rows = _match(
            self._c.conn(), "events", query,
            "AND t.app_id = ? AND t.channel_id = ?",
            (app_id, _chan(channel_id)), limit,
        )
        return [_row_to_event(r) for r in rows]


class SearchableApps(SQLiteApps):
    def search(self, query: str, limit: Optional[int] = None) -> List[App]:
        rows = _match(self._c.conn(), "apps", query, "", (), limit)
        return [App(id=r[0], name=r[1], description=r[2]) for r in rows]


class SearchableEngineInstances(SQLiteEngineInstances):
    def search(
        self, query: str, limit: Optional[int] = None
    ) -> List[EngineInstance]:
        rows = _match(
            self._c.conn(), "engine_instances", query, "", (), limit
        )
        return [self._row(r) for r in rows]


class SearchableEvaluationInstances(SQLiteEvaluationInstances):
    def search(
        self, query: str, limit: Optional[int] = None
    ) -> List[EvaluationInstance]:
        rows = _match(
            self._c.conn(), "evaluation_instances", query, "", (), limit
        )
        return [self._row(r) for r in rows]
