"""EventFrame — columnar event batches that become device arrays.

This is the TPU-native replacement for the reference's ``RDD[Event]``
(``PEvents.find(...)(sc)`` in ``data/.../data/storage/PEvents.scala``,
UNVERIFIED path): instead of a distributed collection of JVM objects, bulk
event reads materialize as host-side columnar arrays, and
:meth:`EventFrame.to_device_arrays` places numeric columns onto a
``jax.sharding.Mesh`` batch axis (padded to the mesh divisor, with a mask) so
DataSources feed sharded jit programs directly.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pio_tpu.data.bimap import BiMap
from pio_tpu.data.event import Event

from pio_tpu.utils.timeutil import to_micros as _to_micros


class EventFrame:
    """A batch of events in column-oriented form."""

    def __init__(
        self,
        event: np.ndarray,
        entity_type: np.ndarray,
        entity_id: np.ndarray,
        target_entity_type: np.ndarray,
        target_entity_id: np.ndarray,
        properties: List[dict],
        event_time_us: np.ndarray,
    ):
        self.event = event
        self.entity_type = entity_type
        self.entity_id = entity_id
        self.target_entity_type = target_entity_type
        self.target_entity_id = target_entity_id
        self.properties = properties
        self.event_time_us = event_time_us

    @classmethod
    def from_events(cls, events: Iterable[Event]) -> "EventFrame":
        evs = list(events)
        return cls(
            event=np.array([e.event for e in evs], dtype=object),
            entity_type=np.array([e.entity_type for e in evs], dtype=object),
            entity_id=np.array([e.entity_id for e in evs], dtype=object),
            target_entity_type=np.array(
                [e.target_entity_type or "" for e in evs], dtype=object
            ),
            target_entity_id=np.array(
                [e.target_entity_id or "" for e in evs], dtype=object
            ),
            properties=[e.properties.to_dict() for e in evs],
            event_time_us=np.array([_to_micros(e.event_time) for e in evs], dtype=np.int64),
        )

    def __len__(self) -> int:
        return len(self.event)

    # -- column extraction --------------------------------------------------
    def property_column(
        self, name: str, dtype=np.float32, default: float = np.nan
    ) -> np.ndarray:
        """Numeric property column; missing values become ``default``."""
        out = np.full(len(self), default, dtype=dtype)
        for i, p in enumerate(self.properties):
            v = p.get(name)
            if v is not None:
                out[i] = v
        return out

    def codes(
        self, column: str, index: Optional[BiMap] = None
    ) -> Tuple[BiMap, np.ndarray]:
        """Index a string column into dense int32 codes.

        Returns (BiMap, codes). Unseen ids under a supplied ``index`` map to
        -1 (callers mask them out).
        """
        col = getattr(self, column)
        if index is None:
            index = BiMap.string_int(col.tolist())
        fwd = index.to_dict()
        codes = np.array([fwd.get(v, -1) for v in col.tolist()], dtype=np.int32)
        return index, codes

    # -- device placement ---------------------------------------------------
    def to_device_arrays(
        self,
        columns: Dict[str, np.ndarray],
        mesh=None,
        axis_name: str = "data",
    ):
        """Place host columns on devices, sharded along the batch dim.

        ``columns`` maps name -> 1-D host array (all equal length). Arrays
        are padded up to a multiple of the mesh *batch-axis* size; the
        returned dict gains a ``"mask"`` float column that is 1 for real
        rows, 0 for pad. Without a mesh, arrays go to the default device
        unsharded. Delegates to :meth:`ComputeContext.shard_batch` — one
        padding/placement implementation.
        """
        from pio_tpu.parallel.context import ComputeContext

        if not columns:
            raise ValueError("no columns given")
        ctx = ComputeContext(mesh=mesh, batch_axis=axis_name)
        return ctx.shard_batch(columns)
