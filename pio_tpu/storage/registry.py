"""Storage registry — env-var-driven backend wiring.

Rebuild of the reference's ``data/.../data/storage/Storage.scala``
(UNVERIFIED path; see SURVEY.md): three repositories (METADATA, EVENTDATA,
MODELDATA) each bound to a named source; sources declare a backend type.

Environment scheme (parity with the reference's ``PIO_STORAGE_*``):

    PIO_STORAGE_REPOSITORIES_METADATA_SOURCE=MYSQLITE
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=MYPARQUET
    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=MYFS
    PIO_STORAGE_SOURCES_MYSQLITE_TYPE=sqlite
    PIO_STORAGE_SOURCES_MYSQLITE_PATH=/path/to/pio.db
    PIO_STORAGE_SOURCES_MYPARQUET_TYPE=parquet
    PIO_STORAGE_SOURCES_MYPARQUET_PATH=/path/to/events
    PIO_STORAGE_SOURCES_MYFS_TYPE=localfs
    PIO_STORAGE_SOURCES_MYFS_PATH=/path/to/models

Unset → quickstart defaults under ``$PIO_TPU_HOME`` (default
``~/.pio_tpu``): SQLite for metadata + events, localfs for models.
Backend types: ``sqlite``, ``memory``, ``parquet`` (events only),
``eventlog`` (events only — native C++ append-only log, the at-scale
event store), ``partlog`` (events only — hash-partitioned, replicated
segment log with leader failover and snapshot compaction), ``localfs``
(models only), ``searchable`` (aliases ``fts``,
``elasticsearch`` — the ES-analog: sqlite + FTS5 full-text search over
events, apps, and run metadata; serves METADATA and EVENTDATA), ``blob``
(models only — content-addressed, URI-schemed store filling the HDFS/S3
slot; ``PATH=file:///...`` today, gs/s3/hdfs register the same SPI).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from pio_tpu.utils import knobs
from pio_tpu.storage import base
from pio_tpu.storage.localfs import LocalFSModels
from pio_tpu.storage.memory import (
    MemAccessKeys,
    MemApps,
    MemChannels,
    MemEngineInstances,
    MemEvaluationInstances,
    MemLEvents,
    MemModels,
    MemPEvents,
)
from pio_tpu.storage.parquet import ParquetPEvents
from pio_tpu.storage.sqlite import (
    SQLiteAccessKeys,
    SQLiteApps,
    SQLiteChannels,
    SQLiteClient,
    SQLiteEngineInstances,
    SQLiteEvaluationInstances,
    SQLiteEvents,
    SQLiteModels,
    SQLitePEvents,
)

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")


class StorageConfigError(base.StorageError):
    pass


_homes_made: set = set()


def pio_home() -> str:
    home = knobs.knob_str("PIO_TPU_HOME")
    if not home:
        home = os.path.join(os.path.expanduser("~"), ".pio_tpu")
    if home not in _homes_made:  # once per path — this sits on the
        os.makedirs(home, exist_ok=True)  # per-request ingest hot path
        _homes_made.add(home)
    return home


class _SourceConfig:
    def __init__(self, name: str, type_: str, path: Optional[str]):
        self.name = name
        self.type = type_
        self.path = path


#: config aliases → canonical backend type ("elasticsearch" lets reference
#: configs select the ES-analog without edits)
_TYPE_ALIASES = {"fts": "searchable", "elasticsearch": "searchable"}


def _source_config(repo: str) -> _SourceConfig:
    src_name = os.environ.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE", "")
    if src_name:
        type_ = os.environ.get(f"PIO_STORAGE_SOURCES_{src_name}_TYPE")
        if not type_:
            raise StorageConfigError(
                f"source {src_name!r} referenced by {repo} has no "
                f"PIO_STORAGE_SOURCES_{src_name}_TYPE"
            )
        path = os.environ.get(f"PIO_STORAGE_SOURCES_{src_name}_PATH")
        t = type_.lower()
        return _SourceConfig(src_name, _TYPE_ALIASES.get(t, t), path)
    # quickstart defaults
    if repo == "MODELDATA":
        return _SourceConfig("DEFAULT_FS", "localfs", None)
    return _SourceConfig("DEFAULT_SQLITE", "sqlite", None)


class Storage:
    """Process-wide registry with per-config caching (thread-safe)."""

    _lock = threading.RLock()
    _clients: Dict[str, object] = {}
    _mem: Dict[str, object] = {}
    _facades: Dict[str, object] = {}  # hot-path store facades (reset-scoped)
    _reset_hooks: list = []  # weakref-wrapped callables

    @classmethod
    def add_reset_hook(cls, hook) -> None:
        """Register a callable invoked by :meth:`reset` — for caches
        OUTSIDE the registry that hold records read through it (e.g. a
        server's positive access-key cache, which must not keep
        authenticating keys from a store that was just reset). Bound
        methods are held weakly so registering never pins a server."""
        import weakref

        try:
            ref = weakref.WeakMethod(hook)
        except TypeError:  # plain function/lambda: hold directly
            ref = (lambda h=hook: h)
        with cls._lock:
            cls._reset_hooks.append(ref)

    # -- internal -----------------------------------------------------------
    @classmethod
    def _sqlite_client(cls, cfg: _SourceConfig) -> SQLiteClient:
        path = cfg.path or os.path.join(pio_home(), "pio.db")
        key = f"sqlite:{path}"
        with cls._lock:
            if key not in cls._clients:
                cls._clients[key] = SQLiteClient(path)
            return cls._clients[key]  # type: ignore[return-value]

    @classmethod
    def _searchable_client(cls, cfg: _SourceConfig):
        from pio_tpu.storage.searchable import SearchableClient

        path = cfg.path or os.path.join(pio_home(), "pio-search.db")
        key = f"searchable:{path}"
        with cls._lock:
            if key not in cls._clients:
                cls._clients[key] = SearchableClient(path)
            return cls._clients[key]

    @classmethod
    def _memory(cls, kind: str, factory):
        with cls._lock:
            if kind not in cls._mem:
                cls._mem[kind] = factory()
            return cls._mem[kind]

    @classmethod
    def reset(cls) -> None:
        """Drop cached clients (tests use this between isolated homes)."""
        with cls._lock:
            cls._clients.clear()
            cls._mem.clear()
            cls._facades.clear()
            hooks = list(cls._reset_hooks)
        _homes_made.clear()  # re-create homes on next touch
        dead = []
        for ref in hooks:  # outside the lock: hooks take their own locks
            hook = ref()
            if hook is None:
                dead.append(ref)
            else:
                hook()
        if dead:
            with cls._lock:
                cls._reset_hooks = [
                    r for r in cls._reset_hooks if r not in dead
                ]

    # -- metadata stores ----------------------------------------------------
    @classmethod
    def _meta(cls, sqlite_cls, mem_kind: str, mem_factory,
              searchable_cls_name: str = ""):
        cfg = _source_config("METADATA")
        if cfg.type == "sqlite":
            return sqlite_cls(cls._sqlite_client(cfg))
        if cfg.type == "memory":
            return cls._memory(mem_kind, mem_factory)
        if cfg.type == "searchable":
            # ES-analog: same relational traits + FTS5 search() where the
            # store has a searchable body (apps, run records). Imported
            # lazily by name so non-searchable deployments never load it.
            from pio_tpu.storage import searchable

            impl = (
                getattr(searchable, searchable_cls_name)
                if searchable_cls_name else sqlite_cls
            )
            return impl(cls._searchable_client(cfg))
        raise StorageConfigError(f"backend {cfg.type!r} cannot serve METADATA")

    @classmethod
    def get_meta_data_apps(cls) -> base.Apps:
        return cls._meta(SQLiteApps, "apps", MemApps, "SearchableApps")

    @classmethod
    def get_meta_data_access_keys(cls) -> base.AccessKeys:
        return cls._meta(SQLiteAccessKeys, "access_keys", MemAccessKeys)

    @classmethod
    def get_meta_data_channels(cls) -> base.Channels:
        return cls._meta(SQLiteChannels, "channels", MemChannels)

    @classmethod
    def get_meta_data_engine_instances(cls) -> base.EngineInstances:
        return cls._meta(
            SQLiteEngineInstances, "engine_instances", MemEngineInstances,
            "SearchableEngineInstances",
        )

    @classmethod
    def get_meta_data_evaluation_instances(cls) -> base.EvaluationInstances:
        return cls._meta(
            SQLiteEvaluationInstances, "evaluation_instances",
            MemEvaluationInstances, "SearchableEvaluationInstances",
        )

    @classmethod
    def _eventlog(cls, cfg: _SourceConfig):
        from pio_tpu.storage.eventlog import EventLogEvents

        path = cfg.path or os.path.join(pio_home(), "eventlog")
        key = f"eventlog:{path}"
        with cls._lock:
            if key not in cls._clients:
                cls._clients[key] = EventLogEvents(path)
            return cls._clients[key]

    @classmethod
    def _partlog(cls, cfg: _SourceConfig):
        from pio_tpu.storage.partlog import PartitionedEventLog

        path = cfg.path or os.path.join(pio_home(), "partlog")
        key = f"partlog:{path}"
        with cls._lock:
            if key not in cls._clients:
                cls._clients[key] = PartitionedEventLog(path)
            return cls._clients[key]

    @classmethod
    def sqlite_clients(cls) -> Dict[str, SQLiteClient]:
        """repository label → SQLiteClient for every repository configured
        on the sqlite backend (opening a client applies pending schema
        migrations). The public surface for maintenance tooling
        (`pio upgrade`); raises StorageConfigError on misconfiguration."""
        out: Dict[str, SQLiteClient] = {}
        for repo in REPOSITORIES:
            cfg = _source_config(repo)
            if cfg.type == "sqlite":
                out[repo] = cls._sqlite_client(cfg)
            elif cfg.type == "searchable":
                # the ES-analog rides the same schema/migration ladder —
                # `pio upgrade` must see it too
                out[repo] = cls._searchable_client(cfg)
        return out

    # -- event stores -------------------------------------------------------
    @classmethod
    def get_levents(cls) -> base.LEvents:
        # the one facade on the per-request ingest hot path: rebuilding
        # it (full config resolution + wrapper allocation) cost
        # ~24 µs/event. Memoized KEYED ON the config env fingerprint —
        # a caller that swaps PIO_STORAGE_*/PIO_TPU_HOME without
        # Storage.reset() still gets the right backend, exactly like
        # the unmemoized behavior (tests re-home per case this way)
        env = os.environ
        src = env.get("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE")
        fp = (env.get("PIO_TPU_HOME"), src)
        if src:
            fp += (env.get(f"PIO_STORAGE_SOURCES_{src}_TYPE"),
                   env.get(f"PIO_STORAGE_SOURCES_{src}_PATH"))
        hit = cls._facades.get("levents")
        if hit is not None and hit[0] == fp:
            return hit[1]
        with cls._lock:
            # build INSIDE the lock: reset() clears _facades under the
            # same lock, so a facade built from pre-reset env config can
            # never be stored into the post-reset cache
            hit = cls._facades.get("levents")
            if hit is not None and hit[0] == fp:
                return hit[1]
            built = cls._build_levents()
            cls._facades["levents"] = (fp, built)
            return built

    @classmethod
    def _build_levents(cls) -> base.LEvents:
        cfg = _source_config("EVENTDATA")
        if cfg.type == "sqlite":
            return SQLiteEvents(cls._sqlite_client(cfg))
        if cfg.type == "memory":
            return cls._memory("levents", MemLEvents)
        if cfg.type == "eventlog":
            return cls._eventlog(cfg)
        if cfg.type == "partlog":
            return cls._partlog(cfg)
        if cfg.type == "searchable":
            from pio_tpu.storage.searchable import SearchableEvents

            return SearchableEvents(cls._searchable_client(cfg))
        if cfg.type == "parquet":
            raise StorageConfigError(
                "parquet backend is bulk-only (PEvents); pair it with sqlite "
                "or memory LEvents via a second source"
            )
        raise StorageConfigError(f"backend {cfg.type!r} cannot serve EVENTDATA")

    @classmethod
    def get_pevents(cls) -> base.PEvents:
        cfg = _source_config("EVENTDATA")
        if cfg.type == "sqlite":
            return SQLitePEvents(SQLiteEvents(cls._sqlite_client(cfg)))
        if cfg.type == "memory":
            return MemPEvents(cls._memory("levents", MemLEvents))
        if cfg.type == "eventlog":
            return base.PEventsAdapter(cls._eventlog(cfg))
        if cfg.type == "partlog":
            return base.PEventsAdapter(cls._partlog(cfg))
        if cfg.type == "searchable":
            from pio_tpu.storage.searchable import SearchableEvents

            return SQLitePEvents(SearchableEvents(cls._searchable_client(cfg)))
        if cfg.type == "parquet":
            path = cfg.path or os.path.join(pio_home(), "events")
            return ParquetPEvents(path)
        raise StorageConfigError(f"backend {cfg.type!r} cannot serve EVENTDATA")

    # -- model store --------------------------------------------------------
    @classmethod
    def get_model_data_models(cls) -> base.Models:
        cfg = _source_config("MODELDATA")
        if cfg.type == "sqlite":
            return SQLiteModels(cls._sqlite_client(cfg))
        if cfg.type == "memory":
            return cls._memory("models", MemModels)
        if cfg.type == "localfs":
            path = cfg.path or os.path.join(pio_home(), "models")
            return LocalFSModels(path)
        if cfg.type == "searchable":
            # model blobs have no searchable body; the plain sqlite trait
            # over the same file serves them
            return SQLiteModels(cls._searchable_client(cfg))
        if cfg.type == "blob":
            from pio_tpu.storage.blobstore import (
                BlobModels, open_blob_backend,
            )

            uri = cfg.path or "file://" + os.path.join(
                pio_home(), "blobmodels"
            )
            return BlobModels(open_blob_backend(uri))
        raise StorageConfigError(f"backend {cfg.type!r} cannot serve MODELDATA")

    # -- health -------------------------------------------------------------
    @classmethod
    def verify_all_data_objects(cls) -> Dict[str, bool]:
        """Connectivity self-check used by ``pio status``
        (reference ``Storage.verifyAllDataObjects``)."""
        out = {}
        checks = {
            "METADATA/apps": cls.get_meta_data_apps,
            "METADATA/access_keys": cls.get_meta_data_access_keys,
            "METADATA/channels": cls.get_meta_data_channels,
            "METADATA/engine_instances": cls.get_meta_data_engine_instances,
            "METADATA/evaluation_instances": cls.get_meta_data_evaluation_instances,
            "EVENTDATA/pevents": cls.get_pevents,
            "MODELDATA/models": cls.get_model_data_models,
        }
        # parquet serves the bulk interface only — probing LEvents there
        # would flag a correctly configured deployment as broken. A broken
        # EVENTDATA config must still be *reported*, not raised.
        try:
            eventdata_type = _source_config("EVENTDATA").type
        except StorageConfigError:
            eventdata_type = None
        if eventdata_type != "parquet":
            checks["EVENTDATA/levents"] = cls.get_levents
        for name, fn in checks.items():
            try:
                fn()
                out[name] = True
            except Exception:
                out[name] = False
        return out
