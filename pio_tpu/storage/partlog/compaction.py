"""Snapshot compaction: fold ``$set/$unset`` chains into entity state.

Training reads aggregate entity properties by replaying every special
event since the beginning of time (``data/aggregation.py``). Compaction
folds each partition's chains ONCE, up to a watermark (the partition's
record count at compaction time), into a per-entity snapshot segment:

    p003/snapshot.json            — the folded state
    p003/snapshot.manifest.json   — sha256 + watermark

written with the model-blob verify-and-fallback discipline: temp file +
fsync + durable rename, and a read that fails sha256 verification falls
back — loudly, with a counter — to the exact full-history fold.
Correctness never rides the cache:

- an entity with NO events past the watermark serves straight from the
  snapshot;
- an entity with newer events RESUMES the fold from snapshot state —
  valid only while the suffix stays in event-time order, so any suffix
  event older than the entity's folded ``max_t_us`` forces a full
  re-fold (``out_of_order``);
- a tombstone or overwrite that rewrote pre-watermark history is caught
  by the per-entity event count (``history_rewritten``) and also
  re-folds.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, List, Optional, Tuple

from pio_tpu.data.datamap import PropertyMap
from pio_tpu.obs import REGISTRY
from pio_tpu.storage.durability import fsync_fileobj, replace_durable
from pio_tpu.utils.timeutil import from_micros, to_micros

log = logging.getLogger("pio_tpu.partlog")

SNAPSHOT_NAME = "snapshot.json"
SNAPSHOT_MANIFEST_NAME = "snapshot.manifest.json"

_COMPACTIONS = REGISTRY.counter(
    "pio_tpu_partlog_compactions_total",
    "Snapshot compactions completed per partition",
    ("partition",),
)
_FALLBACKS = REGISTRY.counter(
    "pio_tpu_partlog_snapshot_fallback_total",
    "Aggregation reads that bypassed the snapshot, by cause",
    ("reason",),
)


class _FoldState:
    """Resumable twin of ``aggregation._PropState`` tracking the extra
    bookkeeping a snapshot needs (max folded event time, event count)."""

    __slots__ = ("fields", "first_us", "last_us", "max_t_us", "n")

    def __init__(self):
        self.fields: Optional[dict] = None
        self.first_us: Optional[int] = None
        self.last_us: Optional[int] = None
        self.max_t_us: Optional[int] = None
        self.n = 0

    @classmethod
    def from_entry(cls, entry: dict) -> "_FoldState":
        s = cls()
        s.fields = (
            dict(entry["fields"]) if entry["fields"] is not None else None
        )
        s.first_us = entry["first_us"]
        s.last_us = entry["last_us"]
        s.max_t_us = entry["max_t_us"]
        s.n = entry["n"]
        return s

    def step(self, e) -> None:
        t_us = to_micros(e.event_time)
        self.n += 1
        if self.max_t_us is None or t_us > self.max_t_us:
            self.max_t_us = t_us
        if e.event == "$set":
            if self.fields is None:
                self.fields = e.properties.to_dict()
                self.first_us = t_us
            else:
                self.fields.update(e.properties.to_dict())
            self.last_us = t_us
        elif e.event == "$unset":
            if self.fields is not None:
                for key in e.properties.keys():
                    self.fields.pop(key, None)
                self.last_us = t_us
        elif e.event == "$delete":
            self.fields = None
            self.first_us = None
            self.last_us = None

    def result(self) -> Optional[PropertyMap]:
        if self.fields is None:
            return None
        return PropertyMap(
            self.fields, from_micros(self.first_us),
            from_micros(self.last_us),
        )


def _fold(rows) -> _FoldState:
    """rows: [(pseq, Event)] in view order; fold in stable time order
    (identical ordering to ``aggregation.fold_properties``)."""
    state = _FoldState()
    for _, e in sorted(rows, key=lambda r: r[1].event_time):
        state.step(e)
    return state


def fold_entities(groups: Dict[Tuple, list]) -> List[dict]:
    """{(app, chan, etype, eid): [(pseq, Event)]} → snapshot entries.
    Entities whose folded state is deleted/never-set are kept (with
    ``fields: null``) so a resumed fold starts from the right state."""
    out = []
    for (a, c, et, ei), rows in groups.items():
        s = _fold(rows)
        out.append({
            "a": a, "c": c, "et": et, "ei": ei,
            "fields": s.fields, "first_us": s.first_us,
            "last_us": s.last_us, "max_t_us": s.max_t_us, "n": s.n,
        })
    return out


def write_snapshot(pdir: str, *, partition: int, watermark: int,
                   entities: List[dict]) -> None:
    """Durably write ``snapshot.json`` + its sha256 manifest."""
    body = json.dumps(
        {
            "version": 1,
            "partition": partition,
            "watermark": watermark,
            "entities": entities,
        },
        sort_keys=True, separators=(",", ":"),
    ).encode()
    digest = hashlib.sha256(body).hexdigest()
    path = os.path.join(pdir, SNAPSHOT_NAME)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(body)
        fsync_fileobj(f)
    replace_durable(tmp, path)
    mpath = os.path.join(pdir, SNAPSHOT_MANIFEST_NAME)
    mtmp = mpath + ".tmp"
    with open(mtmp, "w") as f:
        json.dump({
            "version": 1,
            "sha256": digest,
            "watermark": watermark,
            "entities": len(entities),
        }, f)
        fsync_fileobj(f)
    replace_durable(mtmp, mpath)
    _COMPACTIONS.inc(partition=str(partition))


def load_snapshot(pdir: str) -> Optional[dict]:
    """Verified snapshot → ``{"watermark": int, "entities": {key: entry}}``
    or None (no snapshot, or one that fails verification — the latter is
    loud and counted, never silently served)."""
    mpath = os.path.join(pdir, SNAPSHOT_MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None  # cold: never compacted
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        with open(os.path.join(pdir, SNAPSHOT_NAME), "rb") as f:
            body = f.read()
    except (OSError, ValueError) as e:
        log.warning(
            "partlog snapshot in %s unreadable (%s); falling back to "
            "full-history fold", pdir, e,
        )
        _FALLBACKS.inc(reason="unreadable")
        return None
    if hashlib.sha256(body).hexdigest() != manifest.get("sha256"):
        log.warning(
            "partlog snapshot in %s fails sha256 verification; falling "
            "back to full-history fold", pdir,
        )
        _FALLBACKS.inc(reason="checksum")
        return None
    data = json.loads(body.decode())
    if data.get("watermark") != manifest.get("watermark"):
        log.warning(
            "partlog snapshot in %s disagrees with its manifest "
            "watermark; falling back to full-history fold", pdir,
        )
        _FALLBACKS.inc(reason="checksum")
        return None
    entities = {
        (e["a"], e["c"], e["et"], e["ei"]): e
        for e in data["entities"]
    }
    return {"watermark": data["watermark"], "entities": entities}


def resume_fold(snap: Optional[dict], app_id: int, channel_id,
                entity_type: str, entity_id: str,
                rows: list) -> Optional[PropertyMap]:
    """Fold one entity's special events using the snapshot when it can
    be proven consistent; exact full fold otherwise. ``rows`` is
    ``[(partition, pseq, Event)]`` in view order."""
    pairs = [(pseq, e) for _, pseq, e in rows]
    if snap is None:
        return _fold(pairs).result()
    wm = snap["watermark"]
    prefix = [p for p in pairs if p[0] <= wm]
    suffix = [p for p in pairs if p[0] > wm]
    entry = snap["entities"].get(
        (app_id, channel_id, entity_type, entity_id)
    )
    if entry is None:
        if prefix:
            # pre-watermark events the snapshot never saw: the snapshot
            # predates a rewrite it cannot represent
            _FALLBACKS.inc(reason="history_rewritten")
            return _fold(pairs).result()
        return _fold(suffix).result()  # entity born after the watermark
    if len(prefix) != entry["n"]:
        # a tombstone (or id overwrite) changed pre-watermark history
        _FALLBACKS.inc(reason="history_rewritten")
        return _fold(pairs).result()
    if entry["max_t_us"] is not None and any(
        to_micros(e.event_time) < entry["max_t_us"] for _, e in suffix
    ):
        # an out-of-order suffix event folds BEFORE snapshot state in
        # the exact ordering — resumption would be wrong
        _FALLBACKS.inc(reason="out_of_order")
        return _fold(pairs).result()
    state = _FoldState.from_entry(entry)
    for _, e in sorted(suffix, key=lambda p: p[1].event_time):
        state.step(e)
    return state.result()
