"""Length-prefixed replication protocol: leader → follower streaming.

Wire format (all little-endian): each message is
``<u32 header_len><header JSON>`` followed by exactly ``header["len"]``
raw bytes when the header carries a ``len`` field. Messages:

- leader → follower ``{"op": "hello", "partitions": N}`` — handshake;
- follower → leader ``{"op": "state", "pos": {"0": n0, ...}}`` — the
  follower's verified byte position per partition (its torn tails are
  repaired before reporting, so a leader never re-sends into garbage);
- leader → follower ``{"op": "append", "p": k, "pos": start,
  "len": L}`` + L raw framed-record bytes — must land exactly at the
  follower's current position for partition ``k``;
- follower → leader ``{"op": "ack", "p": k, "pos": end}`` — the bytes
  are on the follower's disk (fsynced per ``PIO_TPU_DURABILITY``).

The leader side (:class:`Replicator`) PULLS from the partition segment
logs rather than queueing blobs: each follower link tracks how far it
has shipped, and catch-up after a reconnect and live streaming are the
same code path — read committed bytes past the follower's position,
send, await ack. Reconnects go through ``retrying()`` with decorrelated
jitter and a per-connect deadline, so a restarting follower is re-joined
without a thundering herd.

Durability gating: at ``PIO_TPU_DURABILITY=commit`` the partition flush
calls :meth:`Replicator.wait_acked` before acking the client — a 201
then means the event is on ``PIO_TPU_REPL_MIN_ACKS`` followers' disks.
``batch``/``os`` replicate asynchronously.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.faults import FaultInjected, failpoint
from pio_tpu.obs import REGISTRY, monotonic_s
from pio_tpu.qos.deadline import Deadline
from pio_tpu.storage import base
from pio_tpu.storage.durability import IntervalSyncer
from pio_tpu.storage.partlog import framing
from pio_tpu.storage.retry import is_transient, retrying
from pio_tpu.utils.envutil import env_int

log = logging.getLogger("pio_tpu.partlog.repl")

#: comma list of follower addresses (``host:port,host:port``) the leader
#: streams to; empty/unset → replication off
REPLICAS_VAR = "PIO_TPU_PARTLOG_REPLICAS"
#: followers whose acks a commit-durability flush must collect
MIN_ACKS_VAR = "PIO_TPU_REPL_MIN_ACKS"
#: how long a commit-durability flush waits for those acks
ACK_TIMEOUT_VAR = "PIO_TPU_REPL_ACK_TIMEOUT_S"
DEFAULT_ACK_TIMEOUT_S = 2.0
#: per-reconnect-attempt deadline fed to retrying()
CONNECT_DEADLINE_VAR = "PIO_TPU_REPL_CONNECT_DEADLINE_S"

_LEN = struct.Struct("<I")
_MAX_CHUNK = 1 << 20  # catch-up read granularity

_REPL_BYTES = REGISTRY.counter(
    "pio_tpu_repl_bytes_total",
    "Framed record bytes shipped to each follower",
    ("follower",),
)
_REPL_ACKS = REGISTRY.counter(
    "pio_tpu_repl_acks_total",
    "Replication appends acknowledged by each follower",
    ("follower",),
)
_REPL_RECONNECTS = REGISTRY.counter(
    "pio_tpu_repl_reconnects_total",
    "Follower connections (re)established by the leader",
    ("follower",),
)
_REPL_LAG = REGISTRY.gauge(
    "pio_tpu_repl_lag_bytes",
    "Leader committed position minus follower acked position",
    ("partition", "follower"),
)
_ACK_SECONDS = REGISTRY.histogram(
    "pio_tpu_repl_ack_seconds",
    "Send-to-ack round trip of one replication append",
    ("partition", "follower"),
)


def replica_addrs() -> List[Tuple[str, int]]:
    """Parse :data:`REPLICAS_VAR`; bad entries are dropped loudly."""
    raw = knobs.knob_str(REPLICAS_VAR).strip()
    out: List[Tuple[str, int]] = []
    if not raw:
        return out
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port = item.rpartition(":")
        try:
            out.append((host or "127.0.0.1", int(port)))
        except ValueError:
            log.warning("ignoring bad %s entry %r", REPLICAS_VAR, item)
    return out


# -- wire helpers ------------------------------------------------------------
def _send_msg(sock: socket.socket, header: dict,
              body: bytes = b"") -> None:
    h = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(h)) + h + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("replication peer closed the stream")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    (hlen,) = _LEN.unpack(_recv_exact(sock, 4))
    if hlen > 1 << 20:
        raise base.StorageError(
            f"replication header of {hlen} bytes exceeds the 1 MiB cap"
        )
    header = json.loads(_recv_exact(sock, hlen).decode())
    body = b""
    blen = int(header.get("len", 0))
    if blen:
        body = _recv_exact(sock, blen)
    return header, body


# -- follower ----------------------------------------------------------------
class FollowerServer:
    """Read-replica process endpoint: mirrors each partition stream into
    one append-only file (``p003.repl``) under ``root``, fsyncing per
    the durability mode, and acks every append. The mirrored files are
    valid framed-record streams, so a :class:`PartitionedEventLog`
    promoted from them (``partlog/failover.py``) serves scans directly —
    read-replica serving is "open the follower root"."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._syncer = IntervalSyncer()
        self._lock = threading.Lock()  # serializes file appends
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.host, self.port = self._srv.getsockname()[:2]
        self._threads: List[threading.Thread] = []
        self._accept = threading.Thread(
            target=self._accept_loop, name="partlog-follower", daemon=True
        )
        self._accept.start()

    def _path(self, partition: int) -> str:
        return os.path.join(self.root, f"p{partition:03d}.repl")

    def positions(self, partitions: int) -> Dict[int, int]:
        """Verified byte position per partition; torn tails (a follower
        crash mid-append) are repaired — loudly — before reporting, so
        the leader resumes from bytes that actually verify."""
        out: Dict[int, int] = {}
        with self._lock:
            for k in range(partitions):
                path = self._path(k)
                # recovery-time truncation: no append may interleave
                # with the repair, so the fsync stays under the lock
                framing.repair(path)  # pio: disable=lock-blocking-call
                out[k] = (
                    os.path.getsize(path) if os.path.exists(path) else 0
                )
        return out

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # listener closed by stop()
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="partlog-follower-conn", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            hello, _ = _recv_msg(conn)
            if hello.get("op") != "hello":
                raise base.StorageError(
                    f"replication handshake expected hello, got "
                    f"{hello.get('op')!r}"
                )
            partitions = int(hello["partitions"])
            # record the topology beside the mirrors: failover promotion
            # reads the partition count from here
            manifest = os.path.join(self.root, "MANIFEST.json")
            if not os.path.exists(manifest):
                with open(manifest, "w") as f:
                    json.dump({"version": 1, "partitions": partitions}, f)
            pos = self.positions(partitions)
            _send_msg(conn, {
                "op": "state",
                "pos": {str(k): v for k, v in pos.items()},
            })
            while not self._stop.is_set():
                header, body = _recv_msg(conn)
                if header.get("op") != "append":
                    raise base.StorageError(
                        f"unexpected replication op {header.get('op')!r}"
                    )
                k = int(header["p"])
                start = int(header["pos"])
                end = self._append(k, start, body)
                failpoint("repl.ack")
                _send_msg(conn, {"op": "ack", "p": k, "pos": end})
        except (ConnectionError, OSError):
            pass  # leader went away; it reconnects and re-handshakes
        except Exception:
            log.exception("follower connection failed")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _append(self, partition: int, start: int, data: bytes) -> int:
        path = self._path(partition)
        with self._lock:
            size = os.path.getsize(path) if os.path.exists(path) else 0
            if start != size:
                # positions are contiguous within a connection and
                # re-negotiated by handshake — a mismatch means the
                # streams diverged; drop the connection, never the data
                raise base.StorageError(
                    f"replication position mismatch for partition "
                    f"{partition}: leader sent {start}, follower is at "
                    f"{size}"
                )
            with open(path, "ab") as f:
                f.write(data)
                f.flush()
                if self._syncer.due(path):
                    os.fsync(f.fileno())
                    self._syncer.mark(path)
            return size + len(data)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=1.0)


# -- leader ------------------------------------------------------------------
class _FollowerLink:
    """Leader-side pump for ONE follower: connect → handshake → stream
    everything past the follower's position, forever."""

    def __init__(self, owner, addr: Tuple[str, int], wake: threading.Condition):
        self.owner = owner  # Replicator
        self.addr = addr
        self.label = f"{addr[0]}:{addr[1]}"
        self.wake = wake
        self.sock: Optional[socket.socket] = None
        self.sent: Dict[int, int] = {}
        self.acked: Dict[int, int] = {}
        self.thread = threading.Thread(
            target=self._run, name=f"partlog-repl-{self.label}", daemon=True
        )

    def _connect(self) -> None:
        failpoint("repl.connect")
        s = socket.create_connection(self.addr, timeout=2.0)
        s.settimeout(5.0)
        try:
            _send_msg(s, {
                "op": "hello", "partitions": self.owner.partitions,
            })
            state, _ = _recv_msg(s)
            if state.get("op") != "state":
                raise base.StorageError(
                    f"replication handshake expected state, got "
                    f"{state.get('op')!r}"
                )
            pos = {int(k): int(v) for k, v in state["pos"].items()}
        except Exception:
            s.close()
            raise
        self.sock = s
        self.sent = dict(pos)
        with self.wake:
            self.acked = dict(pos)
            self.wake.notify_all()
        _REPL_RECONNECTS.inc(follower=self.label)
        log.info("replication link up to %s (positions %s)",
                 self.label, pos)

    def _run(self) -> None:
        deadline_s = knobs.knob_float(CONNECT_DEADLINE_VAR)
        while not self.owner.stopped.is_set():
            if self.sock is None:
                try:
                    # jittered, deadline-bounded reconnect: transient
                    # refusals (follower restarting) retry with
                    # decorrelated backoff; a dead follower surfaces
                    # after the deadline and we go around again
                    retrying(
                        self._connect,
                        site="partlog.repl.connect",
                        attempts=8,
                        base_s=0.05,
                        deadline=Deadline(deadline_s * 1000.0),
                        classify=lambda e: isinstance(
                            e, (OSError, FaultInjected)
                        ) or is_transient(e),
                    )
                except Exception as e:
                    if self.owner.stopped.is_set():
                        return
                    log.warning(
                        "replication connect to %s failed (%s); "
                        "retrying", self.label, e,
                    )
                    self.owner.stopped.wait(0.2)
                    continue
            try:
                progressed = self._pump()
            except (ConnectionError, OSError, base.StorageError,
                    FaultInjected) as e:
                log.warning(
                    "replication link to %s dropped: %s", self.label, e
                )
                self._close_sock()
                continue
            if not progressed:
                with self.wake:
                    self.wake.wait(timeout=0.05)

    def _pump(self) -> bool:
        """Ship one round of pending bytes; returns True on progress."""
        progressed = False
        for k in range(self.owner.partitions):
            committed = self.owner.committed(k)
            sent = self.sent.get(k, 0)
            while sent < committed:
                chunk = self.owner.read_range(
                    k, sent, min(committed, sent + _MAX_CHUNK)
                )
                if not chunk:
                    break
                failpoint("repl.send")
                t0 = monotonic_s()
                _send_msg(self.sock, {
                    "op": "append", "p": k, "pos": sent,
                    "len": len(chunk),
                }, chunk)
                ack, _ = _recv_msg(self.sock)
                if ack.get("op") != "ack" or int(ack.get("p", -1)) != k:
                    raise base.StorageError(
                        f"replication expected ack for partition {k}, "
                        f"got {ack!r}"
                    )
                _ACK_SECONDS.observe(
                    monotonic_s() - t0,
                    partition=str(k), follower=self.label,
                )
                sent = int(ack["pos"])
                self.sent[k] = sent
                _REPL_BYTES.inc(len(chunk), follower=self.label)
                _REPL_ACKS.inc(follower=self.label)
                with self.wake:
                    self.acked[k] = sent
                    self.wake.notify_all()
                progressed = True
            _REPL_LAG.set(
                max(committed - self.acked.get(k, 0), 0),
                partition=str(k), follower=self.label,
            )
        return progressed

    def _close_sock(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class Replicator:
    """Leader-side replication: one :class:`_FollowerLink` per replica
    address, pulling from the owner's partition segment logs."""

    def __init__(self, owner, addrs: List[Tuple[str, int]]):
        #: owner duck type: ``partitions`` (int), ``committed(k)``,
        #: ``read_range(k, start, end)``
        self._owner = owner
        self.partitions = owner.partitions
        self.stopped = threading.Event()
        self._wake = threading.Condition()
        # the unset default is topology-dependent (1 when replicas are
        # configured, 0 standalone) — a computed default the static
        # registry cannot express, so this one read stays on env_int
        # pio: disable=knob-default-drift
        self.min_acks = env_int(
            MIN_ACKS_VAR, 1 if addrs else 0, positive=False
        )
        if self.min_acks > len(addrs):
            # loud misconfiguration, same policy as durability.mode():
            # silently capping to the replica count would quietly weaken
            # the commit-durability guarantee the operator asked for
            raise base.StorageError(
                f"{MIN_ACKS_VAR}={self.min_acks} exceeds the "
                f"{len(addrs)} replica(s) configured in {REPLICAS_VAR}: "
                "commit durability could never collect that many acks"
            )
        self.ack_timeout_s = knobs.knob_float(ACK_TIMEOUT_VAR)
        self._links = [
            _FollowerLink(self, a, self._wake) for a in addrs
        ]
        for link in self._links:
            link.thread.start()

    def committed(self, k: int) -> int:
        return self._owner.committed(k)

    def read_range(self, k: int, start: int, end: int) -> bytes:
        return self._owner.read_range(k, start, end)

    def notify(self) -> None:
        """New committed bytes: wake the link pumps."""
        with self._wake:
            self._wake.notify_all()

    def wait_acked(self, partition: int, pos: int,
                   timeout_s: Optional[float] = None) -> None:
        """Block until ``min_acks`` followers acked ``>= pos`` for the
        partition; raises StorageError on timeout. The commit-durability
        gate: called INSIDE the partition flush, so the group-commit 201
        implies follower durability. The error message deliberately does
        not say "unreachable" — an ack timeout must fail fast to the
        circuit breaker, not burn the request's budget in retries."""
        if timeout_s is None:
            timeout_s = self.ack_timeout_s
        need = self.min_acks  # construction guarantees <= len(links)
        if need <= 0:
            return
        deadline = monotonic_s() + timeout_s
        with self._wake:
            while True:
                got = sum(
                    1 for link in self._links
                    if link.acked.get(partition, 0) >= pos
                )
                if got >= need:
                    return
                remaining = deadline - monotonic_s()
                if remaining <= 0:
                    raise base.StorageError(
                        f"replication ack timeout: {got}/{need} "
                        f"followers acked partition {partition} to "
                        f"{pos} within {timeout_s:.2f}s"
                    )
                self._wake.wait(timeout=remaining)

    # pio: endpoint=/storage.json
    def lag_snapshot(self) -> List[dict]:
        """Topology view: per (follower, partition) acked positions."""
        out = []
        with self._wake:
            for link in self._links:
                out.append({
                    "follower": link.label,
                    "connected": link.sock is not None,
                    "acked": {
                        str(k): link.acked.get(k, 0)
                        for k in range(self.partitions)
                    },
                })
        return out

    def stop(self) -> None:
        self.stopped.set()
        self.notify()
        for link in self._links:
            link._close_sock()
            link.thread.join(timeout=2.0)
