"""Per-partition segment chain: append-only files with CRC framing.

One partition of the partitioned event log is a directory of segment
files (``p003/seg-00000001.log``, ``seg-00000002.log``, …). The
partition is ONE logical byte stream — the concatenation of its
segments in index order — and every position in the replication
protocol, follower handshake and failover election is an offset into
that stream. Segments exist so sealing can hand replication and
compaction immutable units without copying the active file.

Crash discipline (same contract as the native event log):

- the LAST segment may carry a torn tail after a crash; it is repaired
  (truncated, loudly) on open and before the first append after a
  failed write;
- sealed segments are never torn by construction (sealed after a
  clean flush) — a bad crc inside one is corruption and raises.
"""

from __future__ import annotations

import os
import re
import threading
from typing import List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.faults import failpoint
from pio_tpu.obs import REGISTRY
from pio_tpu.storage import base
from pio_tpu.storage.durability import IntervalSyncer, fsync_fileobj
from pio_tpu.storage.partlog import framing

#: active segment seals once it reaches this many bytes (the blob that
#: crosses the line still lands whole — records never split segments)
SEGMENT_BYTES_VAR = "PIO_TPU_PARTLOG_SEGMENT_BYTES"
DEFAULT_SEGMENT_BYTES = 4 * 1024 * 1024

_SEG_RE = re.compile(r"^seg-(\d{8})\.log$")

_APPENDS = REGISTRY.counter(
    "pio_tpu_partlog_appends_total",
    "Record-batch appends per partition of the partitioned event log",
    ("partition",),
)
_SEALED = REGISTRY.counter(
    "pio_tpu_partlog_segments_sealed_total",
    "Segments sealed (rolled over) per partition",
    ("partition",),
)


class SegmentLog:
    """One partition's segment chain; thread-safe."""

    def __init__(self, pdir: str, *, partition: int,
                 syncer: Optional[IntervalSyncer] = None,
                 seg_bytes: Optional[int] = None):
        self.pdir = pdir
        self.partition = partition
        self._label = str(partition)
        self._syncer = syncer or IntervalSyncer()
        self._seg_bytes = (
            seg_bytes if seg_bytes is not None
            else knobs.knob_int(SEGMENT_BYTES_VAR)
        )
        self._lock = threading.RLock()
        os.makedirs(pdir, exist_ok=True)
        #: [(path, committed bytes)] in stream order; on-disk files may be
        #: longer than the recorded size while a torn tail awaits repair —
        #: reads always cap at the recorded (verified) size
        self._segs: List[Tuple[str, int]] = []
        self._fh = None
        self._needs_repair = False
        names = sorted(
            n for n in os.listdir(pdir) if _SEG_RE.match(n)
        )
        for i, name in enumerate(names):
            path = os.path.join(pdir, name)
            if i == len(names) - 1:
                framing.repair(path)  # crash may have torn the last one
            self._segs.append((path, os.path.getsize(path)))
        if not self._segs:
            self._segs.append((self._seg_path(1), 0))

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.pdir, f"seg-{index:08d}.log")

    # -- positions -----------------------------------------------------------
    @property
    def committed(self) -> int:
        """Committed (verified, replicable) length of the stream."""
        with self._lock:
            return sum(size for _, size in self._segs)

    def segments(self) -> List[dict]:
        """Topology view: one dict per segment."""
        with self._lock:
            out, base_off = [], 0
            for path, size in self._segs:
                out.append({
                    "file": os.path.basename(path),
                    "start": base_off,
                    "bytes": size,
                })
                base_off += size
            return out

    # -- append --------------------------------------------------------------
    def append(self, data: bytes) -> Tuple[int, int]:
        """Append framed bytes; returns ``(start, end)`` stream offsets.

        A failed append (torn-write injection, ENOSPC) may leave a torn
        tail on disk past the committed size; the next append repairs it
        first, so new records never land behind unreachable bytes."""
        with self._lock:
            if self._needs_repair:
                self._close_fh()
                path, size = self._segs[-1]
                # torn-tail repair must finish before any append runs,
                # so its fsync deliberately holds the segment lock
                framing.repair(path)  # pio: disable=lock-blocking-call
                if os.path.getsize(path) != size:
                    raise base.StorageError(
                        f"partlog segment {path} lost committed bytes "
                        f"({os.path.getsize(path)} != {size})"
                    )
                self._needs_repair = False
            path, size = self._segs[-1]
            if self._fh is None:
                self._fh = open(path, "ab")
            # fault injection only sleeps when a latency rule is armed
            # (tests); the production path returns immediately
            # pio: disable=lock-blocking-call
            torn = failpoint("partlog.append.before_write", data)
            if torn is not None:
                # injected torn write: persist a strict prefix and fail —
                # the wound a crash mid-append leaves, which the repair
                # pass above must heal before the next append
                self._fh.write(torn)
                self._fh.flush()
                self._needs_repair = True
                raise base.StorageError(
                    f"partlog append failed for partition "
                    f"{self.partition} (injected torn write)"
                )
            try:
                self._fh.write(data)
                self._fh.flush()
            except OSError as e:
                self._needs_repair = True
                raise base.StorageError(
                    f"partlog append failed for partition "
                    f"{self.partition}: {e}"
                )
            if self._syncer.due(path):
                os.fsync(self._fh.fileno())
                self._syncer.mark(path)
            start = self.committed
            new_size = size + len(data)
            self._segs[-1] = (path, new_size)
            end = start + len(data)
            _APPENDS.inc(partition=self._label)
            if new_size >= self._seg_bytes:
                # rollover seals + fsyncs under the lock on purpose:
                # the next append must land in the new segment
                self._seal()  # pio: disable=lock-blocking-call
            return start, end

    def _seal(self) -> None:
        """Roll the active segment: sync it, open the next index."""
        fsync_fileobj(self._fh)  # sealed segments are never torn
        self._close_fh()
        failpoint("partlog.seal")
        index = len(self._segs) + 1
        # index collisions impossible: segment files are never deleted
        # out from under a live handle (compaction writes snapshots
        # beside the chain, it does not rewrite it)
        self._segs.append((self._seg_path(index), 0))
        _SEALED.inc(partition=self._label)

    def _close_fh(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None

    def sync(self) -> None:
        """Force-fsync the active segment (commit-durability flush)."""
        with self._lock:
            if self._fh is not None:
                # the durability flush IS the serialization point —
                # appends must not race the fsync of their own bytes
                # pio: disable=lock-blocking-call
                fsync_fileobj(self._fh)

    # -- reads ---------------------------------------------------------------
    def read_range(self, start: int, end: int) -> bytes:
        """Committed bytes ``[start, end)`` of the logical stream (the
        replication catch-up read). ``end`` is clamped to committed."""
        chunks: List[bytes] = []
        with self._lock:
            end = min(end, self.committed)
            base_off = 0
            for path, size in self._segs:
                seg_end = base_off + size
                if seg_end > start and base_off < end:
                    lo = max(start, base_off) - base_off
                    hi = min(end, seg_end) - base_off
                    with open(path, "rb") as f:
                        f.seek(lo)
                        chunks.append(f.read(hi - lo))
                base_off = seg_end
                if base_off >= end:
                    break
        return b"".join(chunks)

    def payloads(self) -> List[bytes]:
        """Every committed record payload, in stream order. Raises on
        mid-file corruption (a sealed segment with a bad crc)."""
        out: List[bytes] = []
        with self._lock:
            segs = list(self._segs)
        for path, size in segs:
            if size == 0:
                continue
            with open(path, "rb") as f:
                data = f.read(size)
            payloads, verified, total = framing.scan(data, origin=path)
            if verified != total:
                # committed bytes must verify — a short tail here means
                # the file lost data after we recorded the size
                raise base.StorageError(
                    f"corrupt partlog segment {path}: committed bytes "
                    f"fail crc verification at offset {verified}"
                )
            out.extend(payloads)
        return out

    def close(self) -> None:
        with self._lock:
            self._close_fh()
