"""Partitioned, replicated event log with failover and compaction.

Package layout:

- ``framing``     — CRC frame codec + verified-prefix / torn-tail repair
- ``segments``    — one partition's append-only segment chain
- ``partitioned`` — the LEvents backend: router, group commit, views
- ``replication`` — length-prefixed follower streaming + ack gating
- ``compaction``  — snapshot folding with verify-and-fallback reads
- ``failover``    — longest-verified-prefix election and promotion
"""

from pio_tpu.storage.partlog.partitioned import (  # noqa: F401
    DEFAULT_PARTITIONS,
    PARTITIONS_VAR,
    PartitionedEventLog,
    partition_of,
)
