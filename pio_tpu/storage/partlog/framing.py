"""PEL2 record framing for the partitioned log — pure Python.

Same wire layout as the native event-log backend
(``pio_tpu/native/event_log.cpp`` / ``eventlog._encode_record``):
``<u32 len><payload><u32 crc32(payload)>``, little-endian. The crc is
what lets a reader tell "plausible-length garbage at the tail" (a torn
write — the wound a crash mid-append leaves) from committed data.

Classification contract, shared with the native repair pass:

- a bad or incomplete region that extends to END OF FILE is a torn
  tail — expected after a crash; :func:`repair` truncates it (loudly);
- a bad crc FOLLOWED BY more bytes is mid-file corruption — bits rotted
  or someone edited the log; that is never silently healed, it raises
  :class:`~pio_tpu.storage.base.StorageError`.

The replication stream is a concatenation of these frames, so the same
verifier measures a follower's longest verified prefix during failover
election (``partlog/failover.py``).
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import List, Tuple

from pio_tpu.storage import base

log = logging.getLogger("pio_tpu.partlog")

_LEN = struct.Struct("<I")  # pio: frame=pel2-record
#: per-frame overhead: 4-byte length prefix + 4-byte crc trailer
OVERHEAD = 8


def frame(payload: bytes) -> bytes:
    """Frame one record: length prefix + payload + crc32 trailer."""
    return (
        _LEN.pack(len(payload))
        + payload
        + _LEN.pack(zlib.crc32(payload) & 0xFFFFFFFF)
    )


def scan(data: bytes, *, origin: str = "<buf>") -> Tuple[List[bytes], int, int]:
    """Walk framed records in ``data``.

    Returns ``(payloads, verified_end, total)`` where ``verified_end`` is
    the byte offset after the last intact frame; ``verified_end < total``
    means a torn tail follows. Raises :class:`StorageError` when a bad
    frame is followed by more bytes (mid-file corruption, never healed).
    """
    payloads: List[bytes] = []
    off, total = 0, len(data)
    while off < total:
        if off + 4 > total:
            break  # torn: incomplete length prefix at EOF
        (plen,) = _LEN.unpack_from(data, off)
        end = off + 4 + plen + 4
        if end > total:
            break  # torn: frame extends past EOF
        payload = data[off + 4 : off + 4 + plen]
        (crc,) = _LEN.unpack_from(data, off + 4 + plen)
        if crc != (zlib.crc32(payload) & 0xFFFFFFFF):
            if end == total:
                break  # bad region reaches EOF: torn tail
            raise base.StorageError(
                f"corrupt partitioned log: crc mismatch at byte {off} "
                f"of {origin} (bad frame is followed by "
                f"{total - end} more bytes — not a torn tail)"
            )
        payloads.append(payload)
        off = end
    return payloads, off, total


def verified_prefix(path: str) -> int:
    """Byte length of the longest verified frame prefix of ``path``
    (0 for a missing file). The failover-election measure."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return 0
    verified, _, _ = _verified(data, path)
    return verified


def _verified(data: bytes, origin: str) -> Tuple[int, int, List[bytes]]:
    payloads, verified, total = scan(data, origin=origin)
    return verified, total, payloads


def repair(path: str) -> int:
    """Truncate a torn tail off ``path``; returns bytes dropped (0 when
    intact or missing). Loud: every truncation logs a warning with the
    offsets — silent data-dropping is how replicas drift apart. Mid-file
    corruption still raises (see module docstring)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return 0
    verified, total, _ = _verified(data, path)
    dropped = total - verified
    if dropped <= 0:
        return 0
    log.warning(
        "partlog: truncating torn tail of %s: %d bytes dropped "
        "(verified prefix %d of %d)", path, dropped, verified, total,
    )
    with open(path, "r+b") as f:
        f.truncate(verified)
        f.flush()
        os.fsync(f.fileno())
    return dropped
