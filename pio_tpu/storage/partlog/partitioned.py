"""PartitionedEventLog — hash-partitioned, replicated LEvents backend.

The scale-out event store (ROADMAP item 4): where the single-host
backends funnel every ingest through one fsync queue, this backend
hash-partitions events BY ENTITY ID (``crc32(entity_id) % N``) into N
independent segment logs (``partlog/segments.py``), each with its own
group committer — N concurrent fsync queues, N replication streams, and
a failover unit of one partition. The reference gets the same shape from
HBase region splits keyed on its rowkey design (SURVEY.md §2.3); here
the router is explicit and its topology is served at ``/storage.json``.

Records are JSON payloads in PEL2 CRC frames (``partlog/framing.py``):

- ``{"t": "ev", "a": app, "c": chan, "e": {event api dict}}``
- ``{"t": "del", "a": app, "c": chan, "id": event_id}`` — tombstone
- ``{"t": "rm", "a": app, "c": chan}`` — channel purge

Reads serve from an in-memory materialized view replayed from the logs
at open (last-write-wins by event id, tombstones subtract) — the same
read-your-writes contract as the memory backend, rebuilt from disk on
every reopen and on every promoted follower (``partlog/failover.py``).

Registry type: ``PIO_STORAGE_SOURCES_<N>_TYPE=partlog`` (+ ``_PATH``
dir). Knobs: ``PIO_TPU_PARTLOG_PARTITIONS`` (manifest wins on reopen),
``PIO_TPU_PARTLOG_SEGMENT_BYTES``, ``PIO_TPU_PARTLOG_REPLICAS``,
``PIO_TPU_REPL_MIN_ACKS``, ``PIO_TPU_REPL_ACK_TIMEOUT_S``, plus the
global ``PIO_TPU_DURABILITY`` matrix (docs/storage.md).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.data.datamap import DataMap
from pio_tpu.data.event import Event, _parse_time
from pio_tpu.faults import failpoint
from pio_tpu.storage import base
from pio_tpu.storage.durability import (
    IntervalSyncer, fsync_fileobj, mode, replace_durable,
)
from pio_tpu.storage.memory import _match
from pio_tpu.storage.partlog import compaction, framing, replication
from pio_tpu.storage.partlog.segments import SegmentLog
from pio_tpu.utils.timeutil import to_micros

PARTITIONS_VAR = "PIO_TPU_PARTLOG_PARTITIONS"
DEFAULT_PARTITIONS = 4
MANIFEST_NAME = "MANIFEST.json"


def partition_of(entity_id: str, partitions: int) -> int:
    """The partition router: stable hash of the entity id."""
    return zlib.crc32(entity_id.encode("utf-8")) % partitions


def _event_from_api(d: dict) -> Event:
    """Wire dict → Event WITHOUT validation: records were validated on
    their original ingest; replay must not reject what an older rule set
    accepted."""
    return Event(
        event=d["event"],
        entity_type=d["entityType"],
        entity_id=d["entityId"],
        target_entity_type=d.get("targetEntityType"),
        target_entity_id=d.get("targetEntityId"),
        properties=DataMap(d.get("properties") or {}),
        event_time=_parse_time(d.get("eventTime")),
        tags=tuple(d.get("tags") or ()),
        pr_id=d.get("prId"),
        event_id=d.get("eventId"),
        creation_time=_parse_time(d.get("creationTime")),
    )


class _View:
    """Materialized read state replayed from the partition logs.

    ``buckets[(app, chan)][event_id] = (partition, pseq, Event)`` where
    ``pseq`` is the record's 1-based index within its partition — the
    coordinate compaction watermarks are measured in."""

    def __init__(self):
        self.lock = threading.RLock()
        self.buckets: Dict[Tuple[int, Optional[int]], dict] = {}
        #: records applied per partition (the head pseq)
        self.pcounts: Dict[int, int] = {}

    def apply(self, rec: dict, k: int) -> None:
        with self.lock:
            pseq = self.pcounts.get(k, 0) + 1
            self.pcounts[k] = pseq
            key = (rec["a"], rec["c"])
            t = rec["t"]
            if t == "ev":
                e = _event_from_api(rec["e"])
                self.buckets.setdefault(key, {})[e.event_id] = (k, pseq, e)
            elif t == "del":
                self.buckets.setdefault(key, {}).pop(rec["id"], None)
            elif t == "rm":
                # partition-scoped: remove() fans one rm record into
                # EVERY partition, and each clears only the entries its
                # own partition contributed (collectively the N records
                # still clear the bucket). Replay walks partitions
                # sequentially, so a bucket-wide pop here would delete
                # events acked AFTER the purge that routed to a lower-
                # numbered partition — replayed first, then wiped by a
                # later partition's rm record.
                bucket = self.buckets.get(key)
                if bucket is not None:
                    for eid in [
                        eid for eid, row in bucket.items() if row[0] == k
                    ]:
                        del bucket[eid]
                    if not bucket:
                        self.buckets.pop(key, None)
            else:
                raise base.StorageError(
                    f"unknown partlog record type {t!r}"
                )


class _ProbeAll:
    """Duck-typed ``GroupCommitter`` for the event server's liveness
    probe (``_check_group_commit`` looks for a ``_gc`` attribute): a
    partitioned log has N commit locks, and ANY of them wedged means a
    slice of the keyspace can no longer ack."""

    def __init__(self, committers):
        self._committers = committers

    def probe(self, timeout: float = 0.5):
        for k, gc in enumerate(self._committers):
            ok, msg = gc.probe(timeout=timeout)
            if not ok:
                return False, f"partition {k}: {msg}"
        return True, (
            f"all {len(self._committers)} partition commit locks "
            "acquirable"
        )


class PartitionedEventLog(base.LEvents):
    """LEvents over N hash-partitioned segment logs (+ bulk methods the
    :class:`~pio_tpu.storage.base.PEventsAdapter` maps onto PEvents)."""

    def __init__(self, root: str, partitions: Optional[int] = None):
        from pio_tpu.storage.groupcommit import GroupCommitter

        self.root = root
        os.makedirs(root, exist_ok=True)
        self.partitions = self._load_or_init_manifest(partitions)
        self._syncer = IntervalSyncer()
        self._segs = [
            SegmentLog(
                os.path.join(root, f"p{k:03d}"),
                partition=k, syncer=self._syncer,
            )
            for k in range(self.partitions)
        ]
        self._view = _View()
        self._replay()
        # one committer per partition: N independent fsync queues. The
        # store label feeds the groupcommit failpoint, so chaos specs
        # target one leader with `groupcommit.flush.partlog-p0=crash`
        # or the whole router with `groupcommit.flush.partlog*=...`
        self._committers = [
            GroupCommitter(
                (lambda payloads, k=k: self._flush_partition(k, payloads)),
                store=f"partlog-p{k}",
            )
            for k in range(self.partitions)
        ]
        self._gc = _ProbeAll(self._committers)
        self._delete_lock = threading.RLock()
        self._snapshots: Dict[int, Optional[dict]] = {}
        addrs = replication.replica_addrs()
        self._replicator = (
            replication.Replicator(self, addrs) if addrs else None
        )

    # -- manifest ------------------------------------------------------------
    def _load_or_init_manifest(self, partitions: Optional[int]) -> int:
        path = os.path.join(self.root, MANIFEST_NAME)
        if os.path.exists(path):
            with open(path) as f:
                manifest = json.load(f)
            n = int(manifest["partitions"])
            # the manifest wins: repartitioning an existing root would
            # strand every record routed under the old N
            return n
        n = partitions if partitions is not None else knobs.knob_int(
            PARTITIONS_VAR
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "partitions": n}, f)
            fsync_fileobj(f)
        replace_durable(tmp, path)
        return n

    # -- replay / view -------------------------------------------------------
    def _replay(self) -> None:
        for k, seg in enumerate(self._segs):
            for payload in seg.payloads():
                self._view.apply(json.loads(payload.decode()), k)

    # -- replication owner duck type ----------------------------------------
    def committed(self, k: int) -> int:
        return self._segs[k].committed

    def read_range(self, k: int, start: int, end: int) -> bytes:
        return self._segs[k].read_range(start, end)

    # -- encode --------------------------------------------------------------
    @staticmethod
    def _frame_rec(rec: dict) -> bytes:
        return framing.frame(
            json.dumps(rec, separators=(",", ":")).encode()
        )

    def _encode_event(self, event: Event, app_id: int,
                      channel_id) -> Tuple[str, dict, bytes]:
        eid = event.event_id or Event.new_event_id()
        e = event.with_event_id(eid)
        rec = {"t": "ev", "a": app_id, "c": channel_id,
               "e": e.to_api_dict()}
        return eid, rec, self._frame_rec(rec)

    # -- the partition flush (called by each GroupCommitter leader) ----------
    def _flush_partition(self, k: int, payloads) -> List[object]:
        """Append every payload's framed bytes in ONE write, gate on
        follower acks per the durability mode, then advance the view.
        Each payload is a GROUP ``[(result, rec_dict, framed), ...]`` —
        a single insert submits a one-member group, ``insert_batch``
        submits its whole per-partition slice as one payload — so EVERY
        write path serializes through the committer's commit lock and
        segment order always matches view order."""
        from pio_tpu.storage.groupcommit import PartialFlushOutcome

        members = [m for group in payloads for m in group]
        blob = b"".join(framed for _, _, framed in members)
        _, end = self._segs[k].append(blob)
        ack_exc = None
        if self._replicator is not None:
            self._replicator.notify()
            if mode() == "commit":
                # an ack here means min_acks follower DISKS have the
                # bytes; a timeout must fail the WHOLE batch fast. The
                # blob is already on the leader's segment log, so the
                # committer's generic solo retry would re-append every
                # payload — PartialFlushOutcome assigns the error
                # verbatim instead (persisted-but-unacked is never
                # blind-retried).
                try:
                    self._replicator.wait_acked(k, end)
                except base.StorageError as exc:
                    ack_exc = exc
        # the view advances even when acks timed out: the bytes ARE on
        # the leader's disk and a reopen would replay them — the live
        # view and the segment chain must never disagree
        for _, rec, _ in members:
            self._view.apply(rec, k)
        if ack_exc is not None:
            raise PartialFlushOutcome([ack_exc] * len(payloads))
        return [
            [result for result, _, _ in group] for group in payloads
        ]

    # -- LEvents -------------------------------------------------------------
    def init_channel(self, app_id: int, channel_id=None) -> bool:
        return True  # partitions appear on first append

    def insert(self, event: Event, app_id: int, channel_id=None) -> str:
        eid, rec, framed = self._encode_event(event, app_id, channel_id)
        k = partition_of(rec["e"]["entityId"], self.partitions)
        return self._committers[k].submit([(eid, rec, framed)])[0]

    def insert_batch(self, events, app_id: int, channel_id=None):
        """Route the batch by partition, then ONE committer submit per
        partition touched — the whole per-partition slice is one group
        payload, so it lands as one append (the records are self-framed,
        so a concatenation is a valid append sequence — same contract as
        the eventlog backend) and cannot interleave with a concurrent
        committer-led flush on the same partition."""
        if not events:
            return []
        ids: List[str] = []
        groups: Dict[int, list] = {}
        for e in events:
            eid, rec, framed = self._encode_event(e, app_id, channel_id)
            ids.append(eid)
            k = partition_of(rec["e"]["entityId"], self.partitions)
            groups.setdefault(k, []).append((eid, rec, framed))
        for k, members in groups.items():
            self._committers[k].submit(members)
        return ids

    def get(self, event_id: str, app_id: int, channel_id=None):
        with self._view.lock:
            hit = self._view.buckets.get(
                (app_id, channel_id), {}
            ).get(event_id)
        return hit[2] if hit is not None else None

    def delete(self, event_id: str, app_id: int, channel_id=None) -> bool:
        # lock across check + tombstone so two concurrent deletes of one
        # id can't both observe it live (matches the other backends)
        with self._delete_lock:
            ev = self.get(event_id, app_id, channel_id)
            if ev is None:
                return False
            rec = {"t": "del", "a": app_id, "c": channel_id,
                   "id": event_id}
            k = partition_of(ev.entity_id, self.partitions)
            return self._committers[k].submit(
                [(True, rec, self._frame_rec(rec))]
            )[0]

    def find(
        self,
        app_id: int,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=None,
        target_entity_id=None,
        limit=None,
        reversed_order=False,
    ) -> List[Event]:
        failpoint("partlog.scan")
        with self._view.lock:
            rows = list(
                self._view.buckets.get((app_id, channel_id), {}).values()
            )
        evs = [
            e for _, _, e in rows
            if _match(
                e, start_time, until_time, entity_type, entity_id,
                event_names, target_entity_type, target_entity_id,
            )
        ]
        evs.sort(key=lambda e: e.event_time, reverse=reversed_order)
        if limit is not None and limit >= 0:
            evs = evs[:limit]
        return evs

    def remove(self, app_id: int, channel_id=None) -> bool:
        rec = {"t": "rm", "a": app_id, "c": channel_id}
        for k in range(self.partitions):
            self._committers[k].submit(
                [(True, rec, self._frame_rec(rec))]
            )
        return True

    # -- bulk methods (PEventsAdapter maps these onto PEvents) ---------------
    def write(self, events, app_id: int, channel_id=None) -> None:
        self.insert_batch(list(events), app_id, channel_id)

    def delete_bulk(self, event_ids, app_id: int, channel_id=None) -> None:
        """Blind bulk tombstones, batched per partition. A tombstone for
        an absent id is a no-op on read (last-write-wins), identical to
        the eventlog backend's contract."""
        groups: Dict[int, list] = {}
        with self._view.lock:
            bucket = self._view.buckets.get((app_id, channel_id), {})
            for eid in dict.fromkeys(event_ids):
                hit = bucket.get(eid)
                if hit is None:
                    continue
                rec = {"t": "del", "a": app_id, "c": channel_id,
                       "id": eid}
                k = partition_of(hit[2].entity_id, self.partitions)
                groups.setdefault(k, []).append(
                    (True, rec, self._frame_rec(rec))
                )
        for k, members in groups.items():
            self._committers[k].submit(members)

    # -- compaction / snapshot-aware aggregation -----------------------------
    def compact(self) -> Dict[int, int]:
        """Fold each partition's ``$set/$unset/$delete`` chains into a
        per-entity snapshot segment (manifest + sha256 — the model-blob
        verify-and-fallback discipline). Returns {partition: entities}.
        Serving continues throughout: the snapshot is written beside the
        segment chain and swapped in atomically."""
        failpoint("partlog.compact")
        out: Dict[int, int] = {}
        with self._view.lock:
            watermarks = dict(self._view.pcounts)
            per_part = self._special_events_by_partition()
        for k in range(self.partitions):
            watermark = watermarks.get(k, 0)
            entities = compaction.fold_entities(per_part.get(k, {}))
            compaction.write_snapshot(
                self._segs[k].pdir, partition=k,
                watermark=watermark, entities=entities,
            )
            self._snapshots.pop(k, None)  # re-verify on next read
            out[k] = len(entities)
        return out

    def _special_events_by_partition(self) -> Dict[int, dict]:
        """partition → {(app, chan, etype, eid): [(pseq, Event), ...]}
        for every special event in the view (caller holds the lock)."""
        from pio_tpu.data.event import SPECIAL_EVENTS

        per: Dict[int, dict] = {}
        for (a, c), bucket in self._view.buckets.items():
            for k, pseq, e in bucket.values():
                if e.event in SPECIAL_EVENTS:
                    per.setdefault(k, {}).setdefault(
                        (a, c, e.entity_type, e.entity_id), []
                    ).append((pseq, e))
        return per

    def _snapshot(self, k: int) -> Optional[dict]:
        if k not in self._snapshots:
            self._snapshots[k] = compaction.load_snapshot(
                self._segs[k].pdir
            )
        return self._snapshots[k]

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id=None,
        start_time=None,
        until_time=None,
        required=None,
    ) -> dict:
        """Snapshot-aware fold: entities untouched since the compaction
        watermark come straight from the snapshot; entities with newer
        events resume the fold from the snapshot state; anything the
        snapshot cannot prove consistent (out-of-order suffix event,
        rewritten history, checksum mismatch) falls back to the exact
        full-history fold — correctness never rides the cache."""
        if start_time is not None or until_time is not None:
            # snapshots materialize the FULL-range fold only
            return super().aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
                required=required,
            )
        snaps = {k: self._snapshot(k) for k in range(self.partitions)}
        if all(s is None for s in snaps.values()):
            return super().aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                required=required,
            )
        from pio_tpu.data.event import SPECIAL_EVENTS

        with self._view.lock:
            by_entity: Dict[str, list] = {}
            bucket = self._view.buckets.get((app_id, channel_id), {})
            for k, pseq, e in bucket.values():
                if e.event in SPECIAL_EVENTS and e.entity_type == entity_type:
                    by_entity.setdefault(e.entity_id, []).append(
                        (k, pseq, e)
                    )
        out: dict = {}
        for eid, rows in by_entity.items():
            k = rows[0][0]
            pm = compaction.resume_fold(
                snaps[k], app_id, channel_id, entity_type, eid, rows,
            )
            if pm is not None:
                out[eid] = pm
        if required:
            req = set(required)
            out = {
                eid: pm for eid, pm in out.items()
                if req.issubset(pm.keys())
            }
        return out

    # -- topology ------------------------------------------------------------
    # pio: endpoint=/storage.json
    def topology(self) -> dict:
        """The ``/storage.json`` payload: router + per-partition stream
        state + replication positions."""
        parts = []
        for k, seg in enumerate(self._segs):
            with self._view.lock:
                records = self._view.pcounts.get(k, 0)
            snap = self._snapshot(k)
            parts.append({
                "partition": k,
                "committed_bytes": seg.committed,
                "records": records,
                "segments": seg.segments(),
                "snapshot_watermark": (
                    snap["watermark"] if snap else None
                ),
            })
        repl = None
        if self._replicator is not None:
            committed = {
                str(p["partition"]): p["committed_bytes"] for p in parts
            }
            followers = self._replicator.lag_snapshot()
            # ISSUE 11: per-follower lag and per-partition min-acked as
            # first-class fields — the fleet aggregator and its router
            # read the durable floor straight off /storage.json
            for f in followers:
                f["lag"] = {
                    k: max(committed.get(k, 0) - pos, 0)
                    for k, pos in (f.get("acked") or {}).items()
                }
            min_acked = {}
            for k in committed:
                acks = [
                    (f.get("acked") or {}).get(k)
                    for f in followers
                ]
                acks = [a for a in acks if a is not None]
                min_acked[k] = min(acks) if acks else None
            repl = {
                "replicas": [
                    link.label for link in self._replicator._links
                ],
                "min_acks": self._replicator.min_acks,
                "ack_timeout_s": self._replicator.ack_timeout_s,
                "followers": followers,
                "min_acked": min_acked,
            }
        return {
            "backend": "partlog",
            "role": "leader",
            "root": self.root,
            "partitions": self.partitions,
            "router": "crc32(entity_id) % partitions",
            "durability": mode(),
            "partition_detail": parts,
            "replication": repl,
        }

    def close(self) -> None:
        if self._replicator is not None:
            self._replicator.stop()
        for seg in self._segs:
            seg.close()
