"""Leader failover: elect the longest verified prefix, promote it.

When a partition leader dies mid-commit, the surviving follower mirrors
(``replication.FollowerServer`` roots) disagree only in how far each
got. Because every stream is CRC-framed, "how far" is measurable
offline: :func:`elect` scores each candidate by its longest VERIFIED
prefix (torn bytes past the last intact frame never count), and
:func:`promote` assembles a new leader root from the per-partition
winners — each partition's stream becomes the first segment of a fresh
:class:`~pio_tpu.storage.partlog.partitioned.PartitionedEventLog`
chain, torn tails truncated loudly on the way in.

Zero-acked-write-loss argument (the chaos test's invariant): at
``commit`` durability a 201 is sent only after ``min_acks`` followers
fsynced the record (``Replicator.wait_acked`` runs INSIDE the partition
flush), so every acked record is inside at least one candidate's
verified prefix — and the election winner's prefix is at least as long.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional

from pio_tpu.storage import base
from pio_tpu.storage.durability import fsync_fileobj, replace_durable
from pio_tpu.storage.partlog import framing
from pio_tpu.storage.partlog.partitioned import MANIFEST_NAME

log = logging.getLogger("pio_tpu.partlog")


def follower_path(root: str, partition: int) -> str:
    return os.path.join(root, f"p{partition:03d}.repl")


def follower_position(root: str, partition: int) -> int:
    """Verified byte position of one partition mirror (0 if absent)."""
    return framing.verified_prefix(follower_path(root, partition))


def partitions_of(root: str) -> Optional[int]:
    """Partition count from a root's MANIFEST.json (leader or follower
    roots both carry one); None when unreadable."""
    try:
        with open(os.path.join(root, MANIFEST_NAME)) as f:
            return int(json.load(f)["partitions"])
    except (OSError, ValueError, KeyError):
        return None


def elect(candidate_roots: List[str],
          partitions: Optional[int] = None) -> Dict[int, dict]:
    """Per-partition election over follower roots: the candidate with
    the longest verified prefix wins (ties → first candidate, so the
    caller's ordering is the tiebreak)."""
    if partitions is None:
        for root in candidate_roots:
            partitions = partitions_of(root)
            if partitions:
                break
    if not partitions:
        raise base.StorageError(
            "failover election needs a partition count and no candidate "
            "root carries a readable MANIFEST.json"
        )
    out: Dict[int, dict] = {}
    for k in range(partitions):
        scores = {
            root: follower_position(root, k) for root in candidate_roots
        }
        winner = max(candidate_roots, key=lambda r: scores[r])
        out[k] = {
            "partition": k,
            "winner": winner,
            "position": scores[winner],
            "candidates": scores,
        }
    return out


def promote(candidate_roots: List[str], dest_root: str,
            partitions: Optional[int] = None) -> dict:
    """Assemble a promoted leader root at ``dest_root`` from the
    election winners. Each partition's verified stream becomes
    ``pNNN/seg-00000001.log`` (positions are stream offsets, so one
    segment holding the whole prefix is a valid chain). ``dest_root``
    must be absent or empty: a prior incarnation's higher-numbered
    segments or snapshot files would mix into the promoted chain and
    replay rewritten/duplicated history. Returns the election result
    plus the manifest written."""
    election = elect(candidate_roots, partitions)
    n = len(election)
    os.makedirs(dest_root, exist_ok=True)
    stale = sorted(os.listdir(dest_root))
    if stale:
        raise base.StorageError(
            f"failover promote: dest root {dest_root} is not empty "
            f"(found {', '.join(stale[:5])}): stale segments or "
            "snapshots would mix into the promoted chain — promote "
            "into a fresh directory"
        )
    for k, res in election.items():
        pdir = os.path.join(dest_root, f"p{k:03d}")
        os.makedirs(pdir, exist_ok=True)
        src = follower_path(res["winner"], k)
        pos = res["position"]
        data = b""
        if pos > 0:
            with open(src, "rb") as f:
                raw = f.read()
            if len(raw) > pos:
                # torn tail on the winning mirror: never copied forward,
                # and never silently — the operator must see the loss
                log.warning(
                    "partlog promote: dropping %d torn bytes past the "
                    "verified prefix of %s", len(raw) - pos, src,
                )
            data = raw[:pos]
        seg = os.path.join(pdir, "seg-00000001.log")
        tmp = seg + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            fsync_fileobj(f)
        replace_durable(tmp, seg)
        log.info(
            "partlog promote: partition %d ← %s (%d verified bytes)",
            k, res["winner"], pos,
        )
    manifest = os.path.join(dest_root, MANIFEST_NAME)
    tmp = manifest + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": 1, "partitions": n, "promoted": True}, f)
        fsync_fileobj(f)
    replace_durable(tmp, manifest)
    return {"partitions": n, "election": election, "root": dest_root}
