"""Event-log storage backend — native C++ scan over append-only logs.

The high-throughput event store slot: where the reference deploys HBase
with a rowkey design (``storage/hbase/.../HBEventsUtil.scala`` — UNVERIFIED
path; SURVEY.md §2.3) and scans it over the network from Spark executors,
this backend keeps one append-only binary log per (app, channel) on local
disk and does filter/sort/tombstone entirely in C++
(pio_tpu/native/event_log.cpp). Python only frames records on write and
materializes results on read — ``find_frame`` goes log → columnar arenas →
EventFrame with no per-record Python loop on the filter path.

Registry type: ``PIO_STORAGE_SOURCES_<N>_TYPE=eventlog`` (+ ``_PATH`` dir).
"""

from __future__ import annotations

import ctypes
import datetime as _dt
import json
import os
import struct
import threading
import uuid
import zlib
from typing import List, Optional, Sequence

import numpy as np

from pio_tpu.analysis.runtime import make_lock, make_rlock
from pio_tpu.data.datamap import DataMap
from pio_tpu.data.event import Event
from pio_tpu.faults import failpoint
from pio_tpu.storage import base
from pio_tpu.storage.durability import IntervalSyncer
from pio_tpu.storage.frame import EventFrame
from pio_tpu.utils.timeutil import from_micros as _from_us
from pio_tpu.utils.timeutil import to_micros

_I64_MIN = -(2 ** 63)
_I64_MAX = 2 ** 63 - 1

#: one lock per log FILE (realpath), shared by every handle that touches
#: it: a scan racing an in-flight append would read a torn tail record and
#: report the log as corrupt. Per-file (not per-root) so a slow scan of one
#: app's log never blocks other apps. (Cross-process access is not
#: coordinated.)
_file_locks: dict = {}
_file_locks_guard = make_lock("eventlog.locks_guard")


def _lock_for(path: str) -> threading.RLock:
    # re-entrant so delete() can hold it across its get + tombstone append
    key = os.path.realpath(path)
    with _file_locks_guard:
        return _file_locks.setdefault(key, make_rlock(f"eventlog.file:{key}"))


def _to_us(t: Optional[_dt.datetime], default: int) -> int:
    if t is None:
        return default
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return to_micros(t)


def _encode_record(
    flags: int,
    time_us: int,
    ctime_us: int,
    strings: Sequence[bytes],
) -> bytes:
    """Frame one record (see event_log.cpp layout)."""
    assert len(strings) == 9
    for s in strings[:8]:
        if len(s) > 0xFFFF:
            # StorageError, not ValueError: callers catch the SPI error
            # type, and other backends accept the same event
            raise base.StorageError(
                "event string field exceeds the event-log backend's "
                f"64 KiB limit ({len(s)} bytes)"
            )
        # NUL is unrepresentable in the C-ABI filter strings; rejecting it
        # at write time keeps read-side "NUL filter matches nothing" exact
        if flags == 0 and b"\0" in s:
            raise base.StorageError(
                "event string fields may not contain NUL bytes "
                "(event-log backend)"
            )
    header = struct.pack(
        "<Bqq8HI",
        flags,
        time_us,
        ctime_us,
        *(len(s) for s in strings[:8]),
        len(strings[8]),
    )
    payload = header + b"".join(strings)
    # PEL2 framing: length-prefix + payload + crc32 trailer. The crc is
    # what lets the scanner tell "plausible-length garbage at the tail"
    # (a torn write) from committed data — length checks alone can't.
    return (
        struct.pack("<I", len(payload))
        + payload
        + struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF)
    )


class EventLogEvents(base.LEvents, base.PEvents):
    """LEvents + PEvents over per-(app, channel) native logs."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        from pio_tpu.native import event_log_lib
        from pio_tpu.storage.groupcommit import GroupCommitter

        self._lib = event_log_lib()
        self._repaired: set = set()  # paths torn-tail-checked this handle
        self._syncer = IntervalSyncer()  # durability knob: when to fsync
        # instance is registry-cached per root, so this coalesces across
        # concurrent requests (see insert())
        self._gc = GroupCommitter(self._flush_appends, store="eventlog")

    # -- files --------------------------------------------------------------
    def _path(self, app_id: int, channel_id=None) -> str:
        name = f"app_{app_id}"
        if channel_id is not None:
            name += f"_ch{channel_id}"
        return os.path.join(self.root, name + ".pel")

    def _append(self, app_id: int, channel_id, data: bytes) -> None:
        """Locked append; first append per path truncates any torn tail.

        Scans tolerate a torn tail (a crash mid-append), but an append
        after one would land behind unreachable bytes — so repair lazily,
        once per path per handle, before writing.
        """
        path = self._path(app_id, channel_id)
        with _lock_for(path):
            if path not in self._repaired:
                if int(self._lib.pel_repair(path.encode())) < 0:
                    raise base.StorageError(
                        f"event-log repair failed for app {app_id} ({path})"
                    )
                self._repaired.add(path)
            torn = failpoint("eventlog.append.before_write", data)
            if torn is not None:
                # injected torn write: persist only a prefix of the framed
                # bytes and fail — exactly the wound a crash mid-append
                # leaves, which the crc + repair pass must heal on reopen
                self._lib.pel_append(path.encode(), torn, len(torn), 0)
                self._repaired.discard(path)
                raise base.StorageError(
                    f"event-log append failed for app {app_id} "
                    "(injected torn write)"
                )
            sync = self._syncer.due(path)
            rc = self._lib.pel_append(
                path.encode(), data, len(data), 1 if sync else 0
            )
            if rc == 0:
                if sync:
                    self._syncer.mark(path)
                failpoint("eventlog.append.after_write")
            else:
                # a partial fwrite may have left a torn tail: force a
                # re-repair before the next append or later writes would
                # land behind unreachable bytes
                self._repaired.discard(path)
        if rc != 0:
            raise base.StorageError(
                f"event-log append failed for app {app_id}"
            )

    # -- LEvents ------------------------------------------------------------
    def init_channel(self, app_id: int, channel_id=None) -> bool:
        return True  # files appear on first append

    @staticmethod
    def _encode_event(event: Event) -> tuple:
        """→ (event_id, framed record bytes)."""
        event_id = event.event_id or uuid.uuid4().hex
        strings = [
            event_id.encode(),
            event.event.encode(),
            event.entity_type.encode(),
            event.entity_id.encode(),
            (event.target_entity_type or "").encode(),
            (event.target_entity_id or "").encode(),
            (event.pr_id or "").encode(),
            json.dumps(list(event.tags)).encode() if event.tags else b"[]",
            json.dumps(event.properties.to_dict()).encode(),
        ]
        return event_id, _encode_record(
            0,
            _to_us(event.event_time, 0),
            _to_us(event.creation_time, 0),
            strings,
        )

    def insert(self, event: Event, app_id: int, channel_id=None) -> str:
        """Single insert via GROUP COMMIT (storage/groupcommit.py):
        concurrent single-event ingests coalesce into one open/write/
        flush per (app, channel) log — the self-framed records make a
        concatenation a valid append sequence, exactly as insert_batch
        relies on."""
        event_id, rec = self._encode_event(event)
        return self._gc.submit((event_id, app_id, channel_id, rec))

    def _flush_appends(self, payloads):
        """Batched flush over possibly several (app, channel) log files.
        Appends to multiple files cannot be all-or-nothing, so a failed
        group reports per-payload outcomes (PartialFlushOutcome) instead
        of raising wholesale — a blind committer retry would re-append
        the groups that already landed (duplicates in an append-only
        log)."""
        from pio_tpu.storage.groupcommit import PartialFlushOutcome

        failpoint("eventlog.flush.before_write")
        groups: dict = {}
        for k, (eid, app_id, channel_id, rec) in enumerate(payloads):
            groups.setdefault((app_id, channel_id), []).append((k, rec))
        outcomes: list = [None] * len(payloads)
        failed = False
        for (app_id, channel_id), members in groups.items():
            try:
                self._append(
                    app_id, channel_id, b"".join(r for _, r in members)
                )
                for k, _ in members:
                    outcomes[k] = payloads[k][0]
            except Exception as exc:
                failed = True
                for k, _ in members:
                    outcomes[k] = exc
        if failed:
            raise PartialFlushOutcome(outcomes)
        return outcomes

    def insert_batch(self, events, app_id: int, channel_id=None):
        """Frame every record and land them in ONE native append — a
        single open/write/flush of the log instead of one per event (the
        records are self-framed, so a concatenation IS a valid sequence
        of appends; the torn-tail repair contract is unchanged)."""
        if not events:
            return []
        ids, recs = [], []
        for e in events:
            eid, rec = self._encode_event(e)
            ids.append(eid)
            recs.append(rec)
        self._append(app_id, channel_id, b"".join(recs))
        return ids

    @staticmethod
    def _empty_columns() -> dict:
        cols: dict = {
            k: []
            for k in (
                "event_id", "event", "entity_type", "entity_id",
                "target_entity_type", "target_entity_id", "pr_id",
                "tags", "properties",
            )
        }
        cols["time_us"] = np.zeros(0, np.int64)
        cols["ctime_us"] = np.zeros(0, np.int64)
        return cols

    def _scan(
        self,
        app_id: int,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        event_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ):
        """Native scan → (columns dict of lists/arrays). Internal."""
        from pio_tpu.native import PelResult

        names = None if event_names is None else list(event_names)
        if names is not None and not names:
            # [] = "match no event names" (SPI contract, same as the
            # sqlite/memory backends); only None means "any"
            return self._empty_columns()
        # "" is unrepresentable as a native filter (the C ABI uses "" for
        # "any"), and no stored event has an empty value in these fields
        # (validation requires them non-empty when present) — so an
        # explicit empty-string filter matches nothing, as on the other
        # backends.
        filters = (
            entity_type, entity_id, target_entity_type,
            target_entity_id, event_id,
        )
        if "" in filters or any(f and "\0" in f for f in filters):
            # "" and NUL are unrepresentable in the C ABI, and no stored
            # field is empty or NUL-containing (rejected on write) — so
            # these filters match nothing, as on the other backends
            return self._empty_columns()
        names = names or []
        if any("\0" in n for n in names):
            names = [n for n in names if "\0" not in n]
            if not names:
                return self._empty_columns()
        failpoint("eventlog.scan")
        packed = b"".join(n.encode() + b"\0" for n in names)
        res = PelResult()
        path = self._path(app_id, channel_id)
        with _lock_for(path):
            rc = self._lib.pel_scan(
                path.encode(),
                packed,
                len(names),
                (entity_type or "").encode(),
                (entity_id or "").encode(),
                (target_entity_type or "").encode(),
                (target_entity_id or "").encode(),
                (event_id or "").encode(),
                _to_us(start_time, _I64_MIN),
                _to_us(until_time, _I64_MAX),
                1 if reversed_order else 0,
                -1 if limit is None else int(limit),
                ctypes.byref(res),
            )
        if rc == -2:
            raise base.StorageError(
                f"corrupt event log for app {app_id} "
                f"({self._path(app_id, channel_id)})"
            )
        if rc == -3:
            raise base.StorageError(
                f"event-log scan result too large for app {app_id} "
                "(a string column exceeds 4 GiB; narrow the filters)"
            )
        if rc != 0:
            raise base.StorageError(
                f"event-log scan failed for app {app_id} (rc={rc})"
            )
        try:
            n = res.n
            time_us = np.ctypeslib.as_array(res.time_us, shape=(n,)).copy() \
                if n else np.zeros(0, np.int64)
            ctime_us = np.ctypeslib.as_array(
                res.ctime_us, shape=(n,)
            ).copy() if n else np.zeros(0, np.int64)
            cols = []
            for c in range(9):
                if n == 0:
                    cols.append([])
                    continue
                offs = np.ctypeslib.as_array(res.off[c], shape=(n + 1,))
                arena = ctypes.string_at(res.arena[c], int(offs[n]))
                cols.append(
                    [
                        arena[offs[k] : offs[k + 1]].decode()
                        for k in range(n)
                    ]
                )
        finally:
            self._lib.pel_free_result(ctypes.byref(res))
        return {
            "event_id": cols[0],
            "event": cols[1],
            "entity_type": cols[2],
            "entity_id": cols[3],
            "target_entity_type": cols[4],
            "target_entity_id": cols[5],
            "pr_id": cols[6],
            "tags": cols[7],
            "properties": cols[8],
            "time_us": time_us,
            "ctime_us": ctime_us,
        }

    def _to_events(self, cols) -> List[Event]:
        out = []
        for k in range(len(cols["event_id"])):
            out.append(
                Event(
                    event=cols["event"][k],
                    entity_type=cols["entity_type"][k],
                    entity_id=cols["entity_id"][k],
                    target_entity_type=cols["target_entity_type"][k] or None,
                    target_entity_id=cols["target_entity_id"][k] or None,
                    properties=DataMap(json.loads(cols["properties"][k])),
                    event_time=_from_us(cols["time_us"][k]),
                    tags=tuple(json.loads(cols["tags"][k])),
                    pr_id=cols["pr_id"][k] or None,
                    event_id=cols["event_id"][k],
                    creation_time=_from_us(cols["ctime_us"][k]),
                )
            )
        return out

    def get(self, event_id: str, app_id: int, channel_id=None):
        evs = self._to_events(
            self._scan(app_id, channel_id, event_id=event_id, limit=1)
        )
        return evs[0] if evs else None

    def delete(self, event_id: str, app_id: int, channel_id=None) -> bool:
        # lock held across check + tombstone so two concurrent deletes of
        # the same id can't both observe it live and both return True
        # (matches the memory backend's atomic dict.pop)
        with _lock_for(self._path(app_id, channel_id)):
            if self.get(event_id, app_id, channel_id) is None:
                return False
            # tombstone: flags bit0; only the event_id field matters
            rec = _encode_record(
                1, 0, 0, [event_id.encode()] + [b""] * 8
            )
            self._append(app_id, channel_id, rec)
            return True

    def find(
        self,
        app_id: int,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=None,
        target_entity_id=None,
        limit=None,
        reversed_order=False,
    ) -> List[Event]:
        return self._to_events(
            self._scan(
                app_id,
                channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=limit,
                reversed_order=reversed_order,
            )
        )

    def remove(self, app_id: int, channel_id=None) -> bool:
        path = self._path(app_id, channel_id)
        with _lock_for(path):
            self._repaired.discard(path)
            try:
                os.remove(path)
            except FileNotFoundError:
                return False
        return True

    # -- PEvents ------------------------------------------------------------
    def find_frame(self, app_id, channel_id=None, **filters) -> EventFrame:
        cols = self._scan(app_id, channel_id, **filters)
        return EventFrame(
            event=np.array(cols["event"], dtype=object),
            entity_type=np.array(cols["entity_type"], dtype=object),
            entity_id=np.array(cols["entity_id"], dtype=object),
            target_entity_type=np.array(
                cols["target_entity_type"], dtype=object
            ),
            target_entity_id=np.array(
                cols["target_entity_id"], dtype=object
            ),
            properties=[json.loads(p) for p in cols["properties"]],
            event_time_us=cols["time_us"],
        )

    def write(self, events: Sequence[Event], app_id: int, channel_id=None):
        # bulk-import hot path: frame every record, ONE locked append
        recs = b"".join(
            self._encode_event(e)[1] for e in events
        )
        if recs:
            self._append(app_id, channel_id, recs)

    def delete_bulk(self, event_ids, app_id: int, channel_id=None) -> None:
        """Bulk tombstones (PEventsAdapter maps this to PEvents.delete).

        Blind: one batched append of a tombstone per requested id, no read.
        Under last-write-wins a tombstone for an absent or already-deleted
        id is a no-op on read, and any later insert of the id outranks it
        by sequence — identical observable behavior to a checked delete.
        """
        ids = list(dict.fromkeys(event_ids))
        if not ids:
            return
        recs = b"".join(
            _encode_record(1, 0, 0, [eid.encode()] + [b""] * 8)
            for eid in ids
        )
        self._append(app_id, channel_id, recs)

    def compact(self, app_id: int, channel_id=None) -> int:
        """Rewrite the log dropping tombstones and shadowed records;
        returns bytes reclaimed. Atomic (temp file + rename), safe to run
        while serving — in-process readers/writers are excluded by the
        per-file lock for the duration."""
        path = self._path(app_id, channel_id)
        with _lock_for(path):
            n = int(self._lib.pel_compact(path.encode()))
            if n > 0:
                # the REWRITTEN file has no torn tail by construction;
                # n <= 0 means the original (possibly torn) file is still
                # in place and the next append must keep its repair pass
                self._repaired.add(path)
        if n == -2:
            raise base.StorageError(f"corrupt event log for app {app_id}")
        if n < 0:
            raise base.StorageError(
                f"event-log compaction failed for app {app_id} (rc={n})"
            )
        return n

    def count(self, app_id: int, channel_id=None) -> int:
        path = self._path(app_id, channel_id)
        with _lock_for(path):
            n = int(self._lib.pel_count(path.encode()))
        if n == -2:
            raise base.StorageError(f"corrupt event log for app {app_id}")
        if n < 0:
            raise base.StorageError(
                f"event-log read failed for app {app_id} (rc={n})"
            )
        return n
