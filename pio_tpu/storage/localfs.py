"""LocalFS model-blob store.

Rebuild of the reference's ``storage/localfs/.../LocalFSModels.scala``
(UNVERIFIED path; see SURVEY.md): one file per engine-instance id.
"""

from __future__ import annotations

import os
from typing import Optional

from pio_tpu.faults import failpoint
from pio_tpu.storage import base
from pio_tpu.storage.durability import fsync_fileobj, replace_durable
from pio_tpu.storage.records import Model


class LocalFSModels(base.Models):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, model_id: str) -> str:
        safe = model_id.replace("/", "_")
        return os.path.join(self.root, f"{safe}.bin")

    def insert(self, model: Model) -> None:
        tmp = self._path(model.id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model.models)
            # durable rename, half 1: the temp file's BYTES must be on
            # disk before the rename publishes its name — os.replace of
            # an unsynced file can surface as an empty blob after a crash
            fsync_fileobj(f)
        failpoint("storage.localfs.persist")
        # half 2: fsync the parent dir so the rename itself is durable
        replace_durable(tmp, self._path(model.id))

    def get(self, model_id: str) -> Optional[Model]:
        p = self._path(model_id)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return Model(model_id, f.read())

    def delete(self, model_id: str) -> bool:
        p = self._path(model_id)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False
