"""The fsync policy knob: what a 201/ack means on durable media.

One environment variable, ``PIO_TPU_DURABILITY``, read by every backend
that persists bytes:

- ``commit`` — fsync before acking: every event-log group-commit flush
  fsyncs the log, SQLite runs ``synchronous=FULL``, and model persist
  fsyncs the temp file and its parent directory around ``os.replace``.
  An ack survives power loss.
- ``batch`` (default) — fsync at batch granularity: the event-log leader
  fsyncs when :data:`BATCH_SYNC_INTERVAL_S` has elapsed since the last
  sync of that file, SQLite stays on ``synchronous=NORMAL`` (WAL), and
  model persist still gets the full durable rename (models are written
  rarely; losing one to a torn rename costs a retrain). An ack survives
  process death always, power loss up to the sync interval.
- ``os`` — no explicit fsync anywhere and SQLite ``synchronous=OFF``:
  the kernel's writeback policy decides. An ack survives process death
  (the write reached the page cache) but not power loss. This is the
  pre-knob behavior of the localfs/blobstore backends.

The full per-backend matrix is documented in ``docs/storage.md``.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

from pio_tpu.utils import knobs
from pio_tpu.obs import monotonic_s

ENV_VAR = "PIO_TPU_DURABILITY"
MODES = ("commit", "batch", "os")
DEFAULT = "batch"

#: under ``batch``, the event-log leader fsyncs a file at most this often
BATCH_SYNC_INTERVAL_S = 0.05


def mode() -> str:
    """Effective durability mode; raises ValueError on an unknown value
    (misconfigured durability must be loud — a typo'd mode silently
    running ``os`` would void the ack guarantee the operator asked for)."""
    v = knobs.knob_str(ENV_VAR).strip().lower() or DEFAULT
    if v not in MODES:
        raise ValueError(
            f"{ENV_VAR}={v!r} is not one of {'|'.join(MODES)}"
        )
    return v


def fsync_fileobj(f) -> None:
    """Flush + fsync an open file object unless mode is ``os``."""
    if mode() == "os":
        return
    f.flush()
    os.fsync(f.fileno())


def replace_durable(tmp: str, dst: str) -> None:
    """``os.replace`` + (mode permitting) fsync of the parent directory —
    the rename itself is not durable until the directory entry is. The
    temp file must already be synced (:func:`fsync_fileobj` before
    close); this completes the other half of the durable-rename pair."""
    os.replace(tmp, dst)
    if mode() == "os":
        return
    parent = os.path.dirname(os.path.abspath(dst))
    fd = os.open(parent, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class IntervalSyncer:
    """Per-key sync scheduling for ``batch`` mode: ``due(key)`` answers
    "should this write fsync?" per the current mode, and ``mark(key)``
    records that it did. ``commit`` → always, ``os`` → never, ``batch``
    → once per :data:`BATCH_SYNC_INTERVAL_S` per key."""

    def __init__(self, interval_s: float = BATCH_SYNC_INTERVAL_S):
        self._interval_s = interval_s
        self._last: Dict[str, float] = {}
        self._lock = threading.Lock()

    def due(self, key: str) -> bool:
        m = mode()
        if m == "commit":
            return True
        if m == "os":
            return False
        with self._lock:
            last = self._last.get(key)
        return last is None or monotonic_s() - last >= self._interval_s

    def mark(self, key: str) -> None:
        with self._lock:
            self._last[key] = monotonic_s()
