"""Metadata records stored by the meta-data stores.

Rebuild of the reference's ``data/.../data/storage/{Apps,AccessKeys,Channels,
EngineInstances,EvaluationInstances,Models}.scala`` case classes (UNVERIFIED
paths; see SURVEY.md provenance warning).
"""

from __future__ import annotations

import datetime as _dt
import secrets
from dataclasses import asdict, dataclass, field, replace
from typing import Optional, Tuple


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


@dataclass(frozen=True)
class App:
    """A logical application namespace for events (reference ``App``)."""

    id: int
    name: str
    description: Optional[str] = None


@dataclass(frozen=True)
class AccessKey:
    """API key granting event ingest/query for one app.

    ``events`` is the whitelist of event names the key may write; empty means
    all (reference ``AccessKey``).
    """

    key: str
    app_id: int
    events: Tuple[str, ...] = ()

    @staticmethod
    def generate(app_id: int, events: Tuple[str, ...] = ()) -> "AccessKey":
        return AccessKey(key=secrets.token_urlsafe(32), app_id=app_id, events=events)


@dataclass(frozen=True)
class Channel:
    """A named event sub-stream within an app (reference ``Channel``)."""

    id: int
    name: str
    app_id: int

    NAME_CONSTRAINT = "channel names must be 1-16 chars, alphanumeric or '-'"

    @staticmethod
    def is_valid_name(name: str) -> bool:
        return (
            0 < len(name) <= 16
            and all(c.isalnum() or c == "-" for c in name)
        )


class RunStatus:
    """Engine/Evaluation instance lifecycle states (reference status strings)."""

    INIT = "INIT"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    ABORTED = "ABORTED"
    FAILED = "FAILED"


@dataclass(frozen=True)
class EngineInstance:
    """Record of one training run (reference ``EngineInstance``).

    Params are stored as JSON strings, exactly as the reference keeps the
    ``engine.json`` fragments that produced the run.
    """

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: dict = field(default_factory=dict)
    jax_conf: dict = field(default_factory=dict)  # reference: sparkConf
    data_source_params: str = "{}"
    preparator_params: str = "{}"
    algorithms_params: str = "[]"
    serving_params: str = "{}"

    def with_status(self, status: str) -> "EngineInstance":
        return replace(self, status=status, end_time=_utcnow())


@dataclass(frozen=True)
class EvaluationInstance:
    """Record of one evaluation run (reference ``EvaluationInstance``)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: dict = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""

    def with_status(self, status: str) -> "EvaluationInstance":
        return replace(self, status=status, end_time=_utcnow())


@dataclass(frozen=True)
class Model:
    """A trained model blob keyed by engine-instance id (reference ``Model``)."""

    id: str
    models: bytes
