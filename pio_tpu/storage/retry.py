"""Bounded, jittered retries for *transient* storage errors.

The event server's circuit breaker (PR 3) decides when to stop calling a
sick store; this layer decides what to do about the errors that precede
that verdict. A SQLITE_BUSY under a concurrent checkpoint or a blob
server mid-restart is not an outage — retrying it locally converts a
would-be 5xx into a slightly slower 2xx. The wrapper sits INSIDE the
breaker (``_guarded_insert`` wraps the retried call), so the breaker
scores the final outcome: a request saved by retry is a success, a
request that exhausted retries is one failure, not ``attempts`` of them.

Backoff is decorrelated jitter (the AWS-architecture formulation):
``sleep = uniform(base, prev * 3)`` capped — concurrent victims of one
stall don't re-converge into a retry thundering herd. The loop is
deadline-aware via the QoS clock: it never sleeps past ``deadline``,
re-raising the last error instead of burning budget no response can use.
"""

from __future__ import annotations

import random
import sqlite3
import time
from typing import Callable, Optional, TypeVar

from pio_tpu.obs import REGISTRY
from pio_tpu.qos.deadline import Deadline

T = TypeVar("T")

_RETRIES = REGISTRY.counter(
    "pio_tpu_storage_retries_total",
    "Transient storage errors retried by the retrying() wrapper",
    ("site",),
)

#: sqlite3 messages that mean "try again", not "broken": lock/busy states
#: from concurrent writers and WAL checkpoints
_SQLITE_TRANSIENT = ("locked", "busy")


def is_transient(exc: BaseException) -> bool:
    """Default transience classifier.

    - ``sqlite3.OperationalError`` mentioning busy/locked (SQLITE_BUSY /
      SQLITE_LOCKED under WAL contention);
    - :class:`StorageError` for an unreachable blob server (connection
      refused/reset while it restarts);
    - :class:`FaultInjected` — injected ``error`` actions model exactly
      this class of failure, so chaos specs exercise this code path.
    """
    from pio_tpu.faults import FaultInjected
    from pio_tpu.storage.base import StorageError

    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, sqlite3.OperationalError):
        msg = str(exc).lower()
        return any(t in msg for t in _SQLITE_TRANSIENT)
    if isinstance(exc, StorageError):
        return "unreachable" in str(exc).lower()
    return False


def retrying(
    fn: Callable[[], T],
    *,
    site: str = "storage",
    attempts: int = 3,
    base_s: float = 0.02,
    cap_s: float = 0.5,
    deadline: Optional[Deadline] = None,
    classify: Callable[[BaseException], bool] = is_transient,
) -> T:
    """Call ``fn``, retrying transient failures up to ``attempts`` total
    tries. Non-transient errors propagate immediately; so does the last
    transient one once attempts or the deadline run out.
    """
    sleep_s = base_s
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except BaseException as exc:
            if attempt >= attempts or not classify(exc):
                raise
            if deadline is not None and deadline.expired():
                raise
            sleep_s = min(cap_s, random.uniform(base_s, sleep_s * 3))
            if deadline is not None:
                remaining = deadline.remaining_s()
                if remaining <= sleep_s:
                    # a sleep that outlives the deadline retries for a
                    # client that already gave up — fail now instead
                    raise
            _RETRIES.inc(site=site)
            time.sleep(sleep_s)
    raise AssertionError("unreachable")  # loop returns or raises
