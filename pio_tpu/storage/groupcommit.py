"""Leader/follower cross-request write coalescing (group commit).

The reference's event stores amortize durability costs differently —
HBase groups WAL appends server-side, JDBC pools transactions — but the
shape is the same: under concurrent single-event ingest, ONE thread
should pay the commit while its contemporaries ride along.

This is the classic database group-commit protocol, chosen over a
dedicated committer thread because it is FREE for serial traffic: a lone
request enqueues, immediately wins the commit lock, and flushes just its
own payload — no handoff, no extra context switches (the round-3 lesson
from the micro-batcher, whose worker-thread design lost under exactly
one load shape). Under concurrency, threads that arrive while a leader
is mid-flush queue up and the NEXT leader flushes them all in one
backend write.

Durability semantics are unchanged: ``submit`` returns only after the
flush containing the payload completed, so a 201 still means "landed in
the store with the backend's configured durability" — coalescing changes
who performs the write, never when success is reported.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Sequence

from pio_tpu.analysis.runtime import make_lock
from pio_tpu.faults import failpoint
from pio_tpu.obs import REGISTRY, Tracer, active_trace, monotonic_s
from pio_tpu.obs.slog import current_trace_id

#: leader flush duration + coalescing effectiveness, labelled by the
#: owning store (process-global registry: storage has no HTTP surface of
#: its own — the training workflow and event server re-expose these)
_FLUSH_SECONDS = REGISTRY.histogram(
    "pio_tpu_groupcommit_flush_seconds",
    "Group-commit leader flush duration",
    ("store",),
)
_BATCH_SIZE = REGISTRY.histogram(
    "pio_tpu_groupcommit_batch_size",
    "Payloads coalesced per group-commit flush",
    ("store",),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)

#: one trace per leader flush, LINKING the member request traces — the
#: cross-process join point of the event path's waterfall ("which
#: requests rode this flush, and which flush did request X wait on").
#: Feeds ``pio_tpu_commit_stage_seconds``; the event server merges this
#: ring into its ``/traces.json``.
COMMIT_TRACER = Tracer(
    "commit", registry=REGISTRY, stages=("store.flush",), ring=64,
)


class PartialFlushOutcome(Exception):
    """Raised BY a flush callable whose backend cannot make a multi-
    payload write all-or-nothing (e.g. appends across several log
    files): carries one outcome per payload — a result, or an Exception
    for the payloads that failed. The committer assigns them verbatim
    instead of blind-retrying, which would duplicate the payloads that
    already landed."""

    def __init__(self, outcomes):
        super().__init__("partial flush")
        self.outcomes = outcomes


class FlushProtocolError(RuntimeError):
    """A flush (or PartialFlushOutcome) returned a different number of
    outcomes than payloads. The committer cannot tell which payloads
    landed — zip would silently mark the tail done with result=None
    (success with nothing written), and a blind solo retry could
    duplicate the ones that did land — so the whole batch fails."""

    def __init__(self, got: int, expected: int):
        super().__init__(
            f"flush returned {got} outcomes for {expected} payloads"
        )


class _Item:
    __slots__ = ("payload", "done", "result", "exc", "trace_id",
                 "t_submit", "flush_s", "commit_id")

    def __init__(self, payload):
        self.payload = payload
        self.done = threading.Event()
        self.result = None
        self.exc: Any = None
        # trace propagation: the submitting request's trace id rides the
        # item so the leader's flush trace can link its batch-mates
        self.trace_id = current_trace_id()
        self.t_submit = monotonic_s()
        self.flush_s = 0.0          # stamped by the leader
        self.commit_id = None       # the flush trace that carried us


class GroupCommitter:
    """Coalesce concurrent ``submit`` calls into batched ``flush`` calls.

    ``flush(payloads)`` must write every payload ATOMICALLY (one backend
    transaction — nothing persisted if it raises) and return one result
    per payload, in order. If a batched flush raises, each payload is
    retried ALONE so one poisoned write cannot fail its batch-mates;
    per-payload errors re-raise in their own submitting thread. A
    backend that cannot make the batched write all-or-nothing must
    instead raise :class:`PartialFlushOutcome` with per-payload
    outcomes — the committer then assigns them without retrying (a blind
    retry would duplicate the payloads that already landed).
    """

    def __init__(self, flush: Callable[[Sequence[Any]], List[Any]],
                 store: str = "unnamed"):
        self._flush = flush
        self._store = store
        self._q: List[_Item] = []
        self._qlock = make_lock(f"groupcommit.{store}.qlock")
        self._commit_lock = make_lock(f"groupcommit.{store}.commit")

    def submit(self, payload):
        item = _Item(payload)
        with self._qlock:
            self._q.append(item)
        while not item.done.is_set():
            # either become the leader or wait out the current one (whose
            # batch may already include us — it sets done before release)
            if not self._commit_lock.acquire(timeout=0.05):
                continue
            try:
                if item.done.is_set():
                    break
                with self._qlock:
                    batch = self._q
                    self._q = []
                t_flush = monotonic_s()
                _BATCH_SIZE.observe(len(batch), store=self._store)
                # the leader's flush gets its own trace LINKING every
                # member request — the event path's cross-process join
                member_ids = [i.trace_id for i in batch if i.trace_id]
                with COMMIT_TRACER.trace(
                    "commit", links=member_ids,
                    store=self._store, batch=len(batch),
                ) as ctr:
                    try:
                        # inside the try so an injected error lands in the
                        # generic handler (exercising the solo-retry path)
                        # and an injected crash kills the leader MID-FLUSH —
                        # the crash-consistency suite's SIGKILL moment
                        failpoint(f"groupcommit.flush.{self._store}")
                        # list() BEFORE the length check: a generator return
                        # would raise TypeError on len() after the flush
                        # already committed, and the generic handler's solo
                        # retry would then duplicate every payload
                        results = list(
                            self._flush([i.payload for i in batch])
                        )
                        if len(results) != len(batch):
                            raise FlushProtocolError(
                                len(results), len(batch)
                            )
                        for i, r in zip(batch, results):
                            i.result = r
                    except FlushProtocolError as proto:
                        for i in batch:
                            i.exc = proto
                    except PartialFlushOutcome as partial:
                        if len(partial.outcomes) != len(batch):
                            proto = FlushProtocolError(
                                len(partial.outcomes), len(batch)
                            )
                            for i in batch:
                                i.exc = proto
                        else:
                            for i, outcome in zip(batch, partial.outcomes):
                                if isinstance(outcome, Exception):
                                    i.exc = outcome
                                else:
                                    i.result = outcome
                    except Exception:
                        for i in batch:  # isolate the poisoned payload
                            try:
                                i.result = self._flush([i.payload])[0]
                            except Exception as exc:
                                i.exc = exc
                    flush_s = monotonic_s() - t_flush
                    ctr.add_span("store.flush", flush_s, rel_start_s=0.0)
                    if any(i.exc is not None for i in batch):
                        ctr.mark_error()
                _FLUSH_SECONDS.observe(flush_s, store=self._store)
                for i in batch:
                    i.flush_s = flush_s
                    i.commit_id = ctr.trace_id
                    i.done.set()
            finally:
                self._commit_lock.release()
        # attribute the submit on the SUBMITTING request's waterfall:
        # commit_wait (queued behind another leader's flush) + flush
        handle = active_trace()
        if handle is not None:
            total_s = monotonic_s() - item.t_submit
            flush_s = min(item.flush_s, total_s)
            wait_s = max(total_s - flush_s, 0.0)
            rel = handle.elapsed_s - total_s
            if wait_s >= 100e-6:
                handle.add_span("store.commit_wait", wait_s,
                                rel_start_s=rel)
            handle.add_span("store.flush", flush_s,
                            rel_start_s=rel + wait_s)
            if item.commit_id:
                handle.note(commit=item.commit_id)
        if item.exc is not None:
            raise item.exc
        return item.result

    def probe(self, timeout: float = 0.5):
        """Liveness check: try to take the commit lock within ``timeout``.

        Group commit is leader/follower — there is no dedicated thread
        whose aliveness a probe could check. What CAN wedge is the
        commit lock itself (a leader stuck inside a hung backend flush
        holds it forever, and every subsequent submit spins behind it),
        so the health probe measures exactly that: lock acquirable →
        healthy; ``timeout`` elapsed → a flush has been in-flight at
        least that long."""
        if self._commit_lock.acquire(timeout=timeout):
            self._commit_lock.release()
            return True, "commit lock acquirable"
        return False, (
            f"commit lock held > {timeout}s (flush in flight or wedged)"
        )
